"""ResNet family. ≙ reference «python/paddle/vision/models/resnet.py» [U]."""
from __future__ import annotations

from ..nn import (AdaptiveAvgPool2D, BatchNorm2D, Conv2D, Layer, Linear,
                  MaxPool2D, ReLU, Sequential)


class BasicBlock(Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = Conv2D(inplanes, planes, 3, stride, 1, bias_attr=False)
        self.bn1 = BatchNorm2D(planes)
        self.relu = ReLU()
        self.conv2 = Conv2D(planes, planes, 3, 1, 1, bias_attr=False)
        self.bn2 = BatchNorm2D(planes)
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = Conv2D(inplanes, planes, 1, bias_attr=False)
        self.bn1 = BatchNorm2D(planes)
        self.conv2 = Conv2D(planes, planes, 3, stride, 1, bias_attr=False)
        self.bn2 = BatchNorm2D(planes)
        self.conv3 = Conv2D(planes, planes * 4, 1, bias_attr=False)
        self.bn3 = BatchNorm2D(planes * 4)
        self.relu = ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(Layer):
    def __init__(self, block, depth=50, width=64, num_classes=1000,
                 with_pool=True):
        super().__init__()
        layer_cfg = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                     101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
        layers = layer_cfg[depth]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.inplanes = 64
        self.conv1 = Conv2D(3, 64, 7, 2, 3, bias_attr=False)
        self.bn1 = BatchNorm2D(64)
        self.relu = ReLU()
        self.maxpool = MaxPool2D(3, 2, 1)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], 2)
        self.layer3 = self._make_layer(block, 256, layers[2], 2)
        self.layer4 = self._make_layer(block, 512, layers[3], 2)
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = Sequential(
                Conv2D(self.inplanes, planes * block.expansion, 1, stride,
                       bias_attr=False),
                BatchNorm2D(planes * block.expansion))
        layers = [block(self.inplanes, planes, stride, downsample)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes))
        return Sequential(*layers)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def resnet18(pretrained=False, **kwargs):
    return ResNet(BasicBlock, 18, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return ResNet(BasicBlock, 34, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 50, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 101, **kwargs)


# ---------------------------------------------------------------------------
# round-3 zoo additions. ≙ reference «python/paddle/vision/models/{lenet,
# alexnet,vgg,mobilenetv1,mobilenetv2,squeezenet,densenet}.py» [U]
# ---------------------------------------------------------------------------
from ..nn import AvgPool2D, Dropout, ReLU6  # noqa: E402


class LeNet(Layer):
    """≙ paddle.vision.models.LeNet (MNIST-shaped, 1x28x28)."""

    def __init__(self, num_classes=10):
        super().__init__()
        self.num_classes = num_classes
        self.features = Sequential(
            Conv2D(1, 6, 3, stride=1, padding=1), ReLU(),
            MaxPool2D(2, 2),
            Conv2D(6, 16, 5, stride=1, padding=0), ReLU(),
            MaxPool2D(2, 2))
        if num_classes > 0:
            self.fc = Sequential(
                Linear(400, 120), Linear(120, 84), Linear(84, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


class AlexNet(Layer):
    """≙ paddle.vision.models.AlexNet."""

    def __init__(self, num_classes=1000, dropout=0.5):
        super().__init__()
        self.num_classes = num_classes
        self.features = Sequential(
            Conv2D(3, 64, 11, stride=4, padding=2), ReLU(),
            MaxPool2D(3, 2),
            Conv2D(64, 192, 5, padding=2), ReLU(),
            MaxPool2D(3, 2),
            Conv2D(192, 384, 3, padding=1), ReLU(),
            Conv2D(384, 256, 3, padding=1), ReLU(),
            Conv2D(256, 256, 3, padding=1), ReLU(),
            MaxPool2D(3, 2))
        self.avgpool = AdaptiveAvgPool2D((6, 6))
        self.classifier = Sequential(
            Dropout(dropout), Linear(256 * 36, 4096), ReLU(),
            Dropout(dropout), Linear(4096, 4096), ReLU(),
            Linear(4096, num_classes))

    def forward(self, x):
        x = self.avgpool(self.features(x))
        return self.classifier(x.flatten(1))


class VGG(Layer):
    """≙ paddle.vision.models.VGG — features built from a cfg list."""

    CFGS = {
        11: [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512,
             "M"],
        13: [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
             512, 512, "M"],
        16: [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512,
             512, "M", 512, 512, 512, "M"],
        19: [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512,
             512, 512, 512, "M", 512, 512, 512, 512, "M"],
    }

    def __init__(self, features, num_classes=1000, with_pool=True):
        super().__init__()
        self.features = features
        self.num_classes = num_classes
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((7, 7))
        if num_classes > 0:
            self.classifier = Sequential(
                Linear(512 * 49, 4096), ReLU(), Dropout(),
                Linear(4096, 4096), ReLU(), Dropout(),
                Linear(4096, num_classes))

    @staticmethod
    def make_layers(cfg, batch_norm=False):
        layers = []
        c = 3
        for v in cfg:
            if v == "M":
                layers.append(MaxPool2D(2, 2))
            else:
                layers.append(Conv2D(c, v, 3, padding=1))
                if batch_norm:
                    layers.append(BatchNorm2D(v))
                layers.append(ReLU())
                c = v
        return Sequential(*layers)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


def _vgg(depth, batch_norm, **kwargs):
    return VGG(VGG.make_layers(VGG.CFGS[depth], batch_norm), **kwargs)


def vgg11(pretrained=False, batch_norm=False, **kw):
    return _vgg(11, batch_norm, **kw)


def vgg13(pretrained=False, batch_norm=False, **kw):
    return _vgg(13, batch_norm, **kw)


def vgg16(pretrained=False, batch_norm=False, **kw):
    return _vgg(16, batch_norm, **kw)


def vgg19(pretrained=False, batch_norm=False, **kw):
    return _vgg(19, batch_norm, **kw)


class _ConvBNReLU(Sequential):
    def __init__(self, cin, cout, k, stride=1, groups=1, relu6=True):
        p = (k - 1) // 2
        super().__init__(
            Conv2D(cin, cout, k, stride, p, groups=groups, bias_attr=False),
            BatchNorm2D(cout), ReLU6() if relu6 else ReLU())


class MobileNetV1(Layer):
    """≙ paddle.vision.models.MobileNetV1 (depthwise-separable stacks)."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return max(int(ch * scale), 8)
        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
              [(512, 1024, 2), (1024, 1024, 1)]
        feats = [_ConvBNReLU(3, c(32), 3, 2, relu6=False)]
        for cin, cout, s in cfg:
            feats.append(_ConvBNReLU(c(cin), c(cin), 3, s, groups=c(cin),
                                     relu6=False))
            feats.append(_ConvBNReLU(c(cin), c(cout), 1, relu6=False))
        self.features = Sequential(*feats)
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


class InvertedResidual(Layer):
    def __init__(self, cin, cout, stride, expand_ratio):
        super().__init__()
        hidden = int(round(cin * expand_ratio))
        self.use_res = stride == 1 and cin == cout
        layers = []
        if expand_ratio != 1:
            layers.append(_ConvBNReLU(cin, hidden, 1))
        layers += [
            _ConvBNReLU(hidden, hidden, 3, stride, groups=hidden),
            Conv2D(hidden, cout, 1, bias_attr=False),
            BatchNorm2D(cout)]
        self.conv = Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(Layer):
    """≙ paddle.vision.models.MobileNetV2."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]

        def c(ch):
            return max(8, int(ch * scale + 4) // 8 * 8)
        cin = c(32)
        feats = [_ConvBNReLU(3, cin, 3, 2)]
        for t, ch, n, s in cfg:
            cout = c(ch)
            for i in range(n):
                feats.append(InvertedResidual(cin, cout,
                                              s if i == 0 else 1, t))
                cin = cout
        last = c(1280) if scale > 1.0 else 1280
        feats.append(_ConvBNReLU(cin, last, 1))
        self.features = Sequential(*feats)
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = Sequential(Dropout(0.2),
                                         Linear(last, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


class SqueezeNet(Layer):
    """≙ paddle.vision.models.SqueezeNet (1.0/1.1)."""

    class Fire(Layer):
        def __init__(self, cin, squeeze, e1, e3):
            super().__init__()
            self.squeeze = Sequential(Conv2D(cin, squeeze, 1), ReLU())
            self.e1 = Sequential(Conv2D(squeeze, e1, 1), ReLU())
            self.e3 = Sequential(Conv2D(squeeze, e3, 3, padding=1), ReLU())

        def forward(self, x):
            import paddle_tpu as paddle
            s = self.squeeze(x)
            return paddle.concat([self.e1(s), self.e3(s)], axis=1)

    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        F = SqueezeNet.Fire
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = Sequential(
                Conv2D(3, 96, 7, 2), ReLU(), MaxPool2D(3, 2),
                F(96, 16, 64, 64), F(128, 16, 64, 64),
                F(128, 32, 128, 128), MaxPool2D(3, 2),
                F(256, 32, 128, 128), F(256, 48, 192, 192),
                F(384, 48, 192, 192), F(384, 64, 256, 256),
                MaxPool2D(3, 2), F(512, 64, 256, 256))
        else:
            self.features = Sequential(
                Conv2D(3, 64, 3, 2), ReLU(), MaxPool2D(3, 2),
                F(64, 16, 64, 64), F(128, 16, 64, 64), MaxPool2D(3, 2),
                F(128, 32, 128, 128), F(256, 32, 128, 128),
                MaxPool2D(3, 2), F(256, 48, 192, 192),
                F(384, 48, 192, 192), F(384, 64, 256, 256),
                F(512, 64, 256, 256))
        self.classifier = Sequential(
            Dropout(0.5), Conv2D(512, num_classes, 1), ReLU(),
            AdaptiveAvgPool2D((1, 1)))

    def forward(self, x):
        x = self.classifier(self.features(x))
        return x.flatten(1)


class DenseNet(Layer):
    """≙ paddle.vision.models.DenseNet (121/161/169/201/264)."""

    CFGS = {121: (64, 32, [6, 12, 24, 16]),
            161: (96, 48, [6, 12, 36, 24]),
            169: (64, 32, [6, 12, 32, 32]),
            201: (64, 32, [6, 12, 48, 32]),
            264: (64, 32, [6, 12, 64, 48])}

    class _DenseLayer(Layer):
        def __init__(self, cin, growth, bn_size=4):
            super().__init__()
            self.fn = Sequential(
                BatchNorm2D(cin), ReLU(),
                Conv2D(cin, bn_size * growth, 1, bias_attr=False),
                BatchNorm2D(bn_size * growth), ReLU(),
                Conv2D(bn_size * growth, growth, 3, padding=1,
                       bias_attr=False))

        def forward(self, x):
            import paddle_tpu as paddle
            return paddle.concat([x, self.fn(x)], axis=1)

    def __init__(self, layers=121, num_classes=1000, with_pool=True):
        super().__init__()
        init_c, growth, blocks = DenseNet.CFGS[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        feats = [Conv2D(3, init_c, 7, 2, 3, bias_attr=False),
                 BatchNorm2D(init_c), ReLU(), MaxPool2D(3, 2, 1)]
        c = init_c
        for bi, n in enumerate(blocks):
            for _ in range(n):
                feats.append(DenseNet._DenseLayer(c, growth))
                c += growth
            if bi != len(blocks) - 1:
                feats += [BatchNorm2D(c), ReLU(),
                          Conv2D(c, c // 2, 1, bias_attr=False),
                          AvgPool2D(2, 2)]
                c //= 2
        feats += [BatchNorm2D(c), ReLU()]
        self.features = Sequential(*feats)
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = Linear(c, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def alexnet(pretrained=False, **kw):
    return AlexNet(**kw)


def mobilenet_v1(pretrained=False, scale=1.0, **kw):
    return MobileNetV1(scale=scale, **kw)


def mobilenet_v2(pretrained=False, scale=1.0, **kw):
    return MobileNetV2(scale=scale, **kw)


def squeezenet1_0(pretrained=False, **kw):
    return SqueezeNet("1.0", **kw)


def squeezenet1_1(pretrained=False, **kw):
    return SqueezeNet("1.1", **kw)


def densenet121(pretrained=False, **kw):
    return DenseNet(121, **kw)


def densenet201(pretrained=False, **kw):
    return DenseNet(201, **kw)


def resnet152(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 152, **kwargs)


class ShuffleNetV2(Layer):
    """≙ paddle.vision.models.ShuffleNetV2 [U]."""

    class _Unit(Layer):
        def __init__(self, cin, cout, stride):
            super().__init__()
            self.stride = stride
            branch = cout // 2
            if stride == 1:
                inb = cin // 2
            else:
                inb = cin
                self.branch1 = Sequential(
                    Conv2D(inb, inb, 3, stride, 1, groups=inb,
                           bias_attr=False),
                    BatchNorm2D(inb),
                    Conv2D(inb, branch, 1, bias_attr=False),
                    BatchNorm2D(branch), ReLU())
            self.branch2 = Sequential(
                Conv2D(inb, branch, 1, bias_attr=False),
                BatchNorm2D(branch), ReLU(),
                Conv2D(branch, branch, 3, stride, 1, groups=branch,
                       bias_attr=False),
                BatchNorm2D(branch),
                Conv2D(branch, branch, 1, bias_attr=False),
                BatchNorm2D(branch), ReLU())

        def forward(self, x):
            import paddle_tpu as paddle
            if self.stride == 1:
                half = x.shape[1] // 2
                x1, x2 = x[:, :half], x[:, half:]
                out = paddle.concat([x1, self.branch2(x2)], axis=1)
            else:
                out = paddle.concat([self.branch1(x), self.branch2(x)],
                                    axis=1)
            # channel shuffle (groups=2)
            b, c, h, w = out.shape
            out = out.reshape([b, 2, c // 2, h, w]) \
                .transpose([0, 2, 1, 3, 4]).reshape([b, c, h, w])
            return out

    CFGS = {0.5: (48, 96, 192, 1024), 1.0: (116, 232, 464, 1024),
            1.5: (176, 352, 704, 1024), 2.0: (244, 488, 976, 2048)}

    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        c1, c2, c3, cout = ShuffleNetV2.CFGS[scale]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = Sequential(Conv2D(3, 24, 3, 2, 1, bias_attr=False),
                                BatchNorm2D(24), ReLU())
        self.maxpool = MaxPool2D(3, 2, 1)
        feats = []
        cin = 24
        for cstage, n in zip((c1, c2, c3), (4, 8, 4)):
            feats.append(ShuffleNetV2._Unit(cin, cstage, 2))
            for _ in range(n - 1):
                feats.append(ShuffleNetV2._Unit(cstage, cstage, 1))
            cin = cstage
        self.features = Sequential(*feats)
        self.conv_last = Sequential(
            Conv2D(cin, cout, 1, bias_attr=False), BatchNorm2D(cout),
            ReLU())
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = Linear(cout, num_classes)

    def forward(self, x):
        x = self.conv_last(self.features(self.maxpool(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


class GoogLeNet(Layer):
    """≙ paddle.vision.models.GoogLeNet (Inception v1; aux heads omitted
    at inference, returned in training like the reference)."""

    class _Inception(Layer):
        def __init__(self, cin, c1, c3r, c3, c5r, c5, pp):
            super().__init__()
            self.b1 = Sequential(Conv2D(cin, c1, 1), ReLU())
            self.b2 = Sequential(Conv2D(cin, c3r, 1), ReLU(),
                                 Conv2D(c3r, c3, 3, padding=1), ReLU())
            self.b3 = Sequential(Conv2D(cin, c5r, 1), ReLU(),
                                 Conv2D(c5r, c5, 5, padding=2), ReLU())
            self.b4 = Sequential(MaxPool2D(3, 1, 1),
                                 Conv2D(cin, pp, 1), ReLU())

        def forward(self, x):
            import paddle_tpu as paddle
            return paddle.concat(
                [self.b1(x), self.b2(x), self.b3(x), self.b4(x)], axis=1)

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        I = GoogLeNet._Inception
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = Sequential(
            Conv2D(3, 64, 7, 2, 3), ReLU(), MaxPool2D(3, 2, 1),
            Conv2D(64, 64, 1), ReLU(),
            Conv2D(64, 192, 3, padding=1), ReLU(), MaxPool2D(3, 2, 1))
        self.blocks = Sequential(
            I(192, 64, 96, 128, 16, 32, 32),
            I(256, 128, 128, 192, 32, 96, 64), MaxPool2D(3, 2, 1),
            I(480, 192, 96, 208, 16, 48, 64),
            I(512, 160, 112, 224, 24, 64, 64),
            I(512, 128, 128, 256, 24, 64, 64),
            I(512, 112, 144, 288, 32, 64, 64),
            I(528, 256, 160, 320, 32, 128, 128), MaxPool2D(3, 2, 1),
            I(832, 256, 160, 320, 32, 128, 128),
            I(832, 384, 192, 384, 48, 128, 128))
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.head = Sequential(Dropout(0.2), Linear(1024, num_classes))

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.head(x.flatten(1))
        return x


def shufflenet_v2_x0_25(pretrained=False, **kw):
    return ShuffleNetV2(scale=0.5, **kw)


def shufflenet_v2_x0_5(pretrained=False, **kw):
    return ShuffleNetV2(scale=0.5, **kw)


def shufflenet_v2_x1_0(pretrained=False, **kw):
    return ShuffleNetV2(scale=1.0, **kw)


def shufflenet_v2_x1_5(pretrained=False, **kw):
    return ShuffleNetV2(scale=1.5, **kw)


def shufflenet_v2_x2_0(pretrained=False, **kw):
    return ShuffleNetV2(scale=2.0, **kw)


def googlenet(pretrained=False, **kw):
    return GoogLeNet(**kw)
