"""Vision datasets. ≙ reference «python/paddle/vision/datasets/» (MNIST,
Cifar10/100, DatasetFolder, FakeData-style synthetic) [U].

Offline-first design (this image has no network): the classes parse the
STANDARD local file formats — MNIST idx, CIFAR python pickles, image
directory trees — from a user-supplied path instead of downloading, and
`FakeData` provides deterministic synthetic images so every recipe and
test runs with zero data files.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
from typing import Callable, Optional

import numpy as np

from ..io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeData",
           "DatasetFolder", "ImageFolder"]


def _maybe_gzip_open(path):
    return gzip.open(path, "rb") if path.endswith(".gz") else \
        open(path, "rb")


def _read_idx(path):
    """Parse MNIST idx files (ubyte images/labels; .gz transparent)."""
    with _maybe_gzip_open(path) as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = [struct.unpack(">I", f.read(4))[0] for _ in range(ndim)]
        data = np.frombuffer(f.read(), np.uint8)
    return data.reshape(dims)


class MNIST(Dataset):
    """MNIST from local idx files.

    image_path/label_path point at (optionally gzipped) idx files, e.g.
    train-images-idx3-ubyte.gz. mode selects conventional filenames when
    only a directory is given via `root`.
    """

    _FILES = {
        "train": ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
        "test": ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
    }

    def __init__(self, image_path: Optional[str] = None,
                 label_path: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None, root=None,
                 backend: str = "cv2", download: bool = False):
        if download:
            raise RuntimeError(
                "offline environment: place the idx files locally and "
                "pass image_path/label_path (or root)")
        if root is not None and image_path is None:
            img, lab = self._FILES[mode]
            for suffix in ("", ".gz"):
                p = os.path.join(root, img + suffix)
                if os.path.exists(p):
                    image_path = p
                    label_path = os.path.join(root, lab + suffix)
                    break
        if image_path is None or label_path is None:
            raise FileNotFoundError(
                "MNIST: provide image_path/label_path or a root directory "
                "containing the idx files")
        self.images = _read_idx(image_path)        # (N, 28, 28) uint8
        self.labels = _read_idx(label_path).astype(np.int64)
        self.transform = transform

    def __len__(self):
        return len(self.images)

    def __getitem__(self, i):
        img = self.images[i]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[i]


class FashionMNIST(MNIST):
    """Same idx format, different corpus."""


class Cifar10(Dataset):
    """CIFAR-10 from the local python-pickle archive directory (the
    extracted cifar-10-batches-py/) or a single batch file."""

    _TRAIN = [f"data_batch_{i}" for i in range(1, 6)]
    _TEST = ["test_batch"]
    _SUBDIR = "cifar-10-batches-py"

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None, root=None,
                 backend: str = "cv2", download: bool = False):
        if download:
            raise RuntimeError(
                "offline environment: extract cifar-10-batches-py locally "
                "and pass data_file or root")
        names = self._TRAIN if mode == "train" else self._TEST
        files = []
        if data_file is not None:
            files = [data_file]
        elif root is not None:
            sub = os.path.join(root, self._SUBDIR)
            base = sub if os.path.isdir(sub) else root
            files = [os.path.join(base, n) for n in names
                     if os.path.exists(os.path.join(base, n))]
        if not files:
            raise FileNotFoundError("Cifar10: no batch files found")
        xs, ys = [], []
        for fp in files:
            with open(fp, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(np.asarray(d[b"data"], np.uint8))
            ys.extend(d.get(b"labels", d.get(b"fine_labels")))
        self.images = np.concatenate(xs).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(ys, np.int64)
        self.transform = transform

    def __len__(self):
        return len(self.images)

    def __getitem__(self, i):
        img = self.images[i]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[i]


class Cifar100(Cifar10):
    _TRAIN = ["train"]
    _TEST = ["test"]
    _SUBDIR = "cifar-100-python"


class FakeData(Dataset):
    """Deterministic synthetic images (≙ torchvision FakeData): the
    offline stand-in every vision recipe/test can run on."""

    def __init__(self, size=1000, image_shape=(3, 32, 32), num_classes=10,
                 transform: Optional[Callable] = None, seed: int = 0):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __len__(self):
        return self.size

    def __getitem__(self, i):
        rng = np.random.default_rng(self.seed * 1_000_003 + i)
        img = rng.integers(0, 256, self.image_shape,
                           dtype=np.uint8).astype(np.float32) / 255.0
        label = int(rng.integers(0, self.num_classes))
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(label)


_IMG_EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp")


class DatasetFolder(Dataset):
    """class-per-subdirectory image tree (≙ paddle.vision DatasetFolder):
    root/class_x/xxx.png -> (image, class_index). Requires PIL."""

    def __init__(self, root: str, transform: Optional[Callable] = None,
                 extensions=_IMG_EXTS, loader: Optional[Callable] = None):
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fn in sorted(os.listdir(cdir)):
                if fn.lower().endswith(tuple(extensions)):
                    self.samples.append((os.path.join(cdir, fn),
                                         self.class_to_idx[c]))
        if not self.samples:
            raise FileNotFoundError(f"no images under {root}")
        self.transform = transform
        self.loader = loader or self._pil_loader

    @staticmethod
    def _pil_loader(path):
        from PIL import Image
        with Image.open(path) as im:
            return np.asarray(im.convert("RGB"))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        path, target = self.samples[i]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(target)


class ImageFolder(DatasetFolder):
    """Flat/recursive image directory without labels (label = 0)."""

    def __init__(self, root: str, transform: Optional[Callable] = None,
                 extensions=_IMG_EXTS, loader: Optional[Callable] = None):
        self.samples = []
        for dirpath, dirnames, files in os.walk(root):
            dirnames.sort()  # deterministic traversal across filesystems
            for fn in sorted(files):
                if fn.lower().endswith(tuple(extensions)):
                    self.samples.append((os.path.join(dirpath, fn), 0))
        if not self.samples:
            raise FileNotFoundError(f"no images under {root}")
        self.transform = transform
        self.loader = loader or self._pil_loader
        self.class_to_idx = {}

    def __getitem__(self, i):
        path, _ = self.samples[i]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img
