"""paddle_tpu.vision.ops — detection/vision operators.

≙ reference «python/paddle/vision/ops.py» + PHI detection kernels
(«paddle/phi/kernels/*/nms_kernel*», «roi_align_kernel*»,
«deformable_conv_kernel*» [U]; SURVEY.md §2.2 vision row). TPU-first
notes per op:

* `nms` — iterative suppression is sequential by nature; implemented as a
  `lax.while_loop` over a boolean keep-mask (static shapes, jittable).
  The returned index list is eager-only (dynamic length), matching the
  reference's dynamic output; under jit use the mask helper `_nms_mask`.
* `roi_align` / `roi_pool` — bilinear gather + mean/max over a static
  (out_h, out_w, samples) grid: pure gather/reduce, MXU-free but
  vectorized over ROIs via vmap.
* `deform_conv2d` — offset-guided bilinear gather to an im2col patch
  tensor, then ONE big matmul (the MXU does the work; the reference's
  CUDA kernel interleaves gather+mac instead).
* box utils (`box_coder`, `box_area`, `box_iou`) — elementwise.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor, apply, to_tensor

__all__ = ["nms", "box_area", "box_iou", "box_coder", "roi_align",
           "roi_pool", "deform_conv2d", "DeformConv2D", "RoIAlign",
           "RoIPool"]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


# ---------------------------------------------------------------------------
# boxes
# ---------------------------------------------------------------------------
def box_area(boxes):
    """(N, 4) xyxy -> (N,) areas."""
    def fn(b):
        return (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return apply("box_area", fn, (_t(boxes),))


def _iou_matrix(a, b):
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return inter / jnp.maximum(area_a[:, None] + area_b[None, :] - inter,
                               1e-10)


def box_iou(boxes1, boxes2):
    """Pairwise IoU: (N, 4) x (M, 4) -> (N, M)."""
    return apply("box_iou", _iou_matrix, (_t(boxes1), _t(boxes2)))


def _nms_mask_values(boxes, scores, iou_threshold):
    """Greedy NMS as a jittable fixed-shape program. Returns a bool keep
    mask; equivalent to suppressing in descending-score order."""
    n = boxes.shape[0]
    order = jnp.argsort(-scores)
    b = jnp.take(boxes, order, axis=0)
    iou = _iou_matrix(b, b)

    def body(i, keep):
        # suppress j > i iff keep[i] and iou(i, j) > thr
        sup = (iou[i] > iou_threshold) & keep[i] \
            & (jnp.arange(n) > i)
        return keep & ~sup

    keep_sorted = lax.fori_loop(0, n, body, jnp.ones((n,), bool))
    # unsort back to input order
    inv = jnp.zeros((n,), jnp.int32).at[order].set(jnp.arange(n))
    return jnp.take(keep_sorted, inv)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """≙ paddle.vision.ops.nms. Returns kept indices sorted by descending
    score (dynamic length — eager only, like the reference's GPU op;
    use the mask from inside jit)."""
    boxes_t = _t(boxes)
    if scores is None:
        scores_t = to_tensor(np.arange(boxes_t.shape[0], 0, -1,
                                       dtype=np.float32))
    else:
        scores_t = _t(scores)

    if category_idxs is not None:
        # per-category NMS: offset boxes per category so they never overlap
        cat = _t(category_idxs)

        def shift(b, c):
            off = c.astype(b.dtype)[:, None] * (
                jnp.max(b) - jnp.min(b) + 1.0)
            return b + off
        boxes_for_iou = apply("nms_cat_shift", shift, (boxes_t, cat))
    else:
        boxes_for_iou = boxes_t

    keep = apply(
        "nms_mask",
        lambda b, s: _nms_mask_values(b, s, float(iou_threshold)),
        (boxes_for_iou, scores_t))
    keep_np = np.asarray(keep._value)
    scores_np = np.asarray(scores_t._value)
    idx = np.nonzero(keep_np)[0]
    idx = idx[np.argsort(-scores_np[idx], kind="stable")]
    if top_k is not None:
        idx = idx[:top_k]
    return to_tensor(idx.astype(np.int64))


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """≙ paddle.vision.ops.box_coder (encode/decode between corner boxes
    and center-size offsets)."""
    pb, tb = _t(prior_box), _t(target_box)
    pbv = _t(prior_box_var) if not np.isscalar(prior_box_var) \
        and not isinstance(prior_box_var, (list, tuple)) else prior_box_var
    norm = 1.0 if box_normalized else 0.0

    def dims(p):
        w = p[..., 2] - p[..., 0] + (1.0 - norm)
        h = p[..., 3] - p[..., 1] + (1.0 - norm)
        cx = p[..., 0] + w * 0.5
        cy = p[..., 1] + h * 0.5
        return w, h, cx, cy

    def var_of(p_shape):
        if isinstance(pbv, (int, float)):
            return jnp.full(p_shape[:-1] + (4,), float(pbv))
        if isinstance(pbv, (list, tuple)):
            return jnp.broadcast_to(jnp.asarray(pbv, jnp.float32),
                                    p_shape[:-1] + (4,))
        return None

    if code_type == "encode_center_size":
        def fn(p, t, *v):
            pw, ph, pcx, pcy = dims(p[None, :, :])      # (1, M, 4) dims
            tw, th, tcx, tcy = dims(t[:, None, :])      # (N, 1, 4) dims
            out = jnp.stack([(tcx - pcx) / pw, (tcy - pcy) / ph,
                             jnp.log(tw / pw), jnp.log(th / ph)], axis=-1)
            vv = v[0][None, :, :] if v else var_of(out.shape)
            return out / vv if vv is not None else out
        args = (pb, tb) + ((pbv,) if isinstance(pbv, Tensor) else ())
        return apply("box_encode", fn, args)
    elif code_type == "decode_center_size":
        def fn(p, t, *v):
            if axis == 0:
                pq = p[None, :, :]
            else:
                pq = p[:, None, :]
            pw, ph, pcx, pcy = dims(pq)
            vv = v[0] if v else var_of(t.shape)
            if vv is not None:
                if isinstance(pbv, Tensor):
                    vv = vv[None, :, :] if axis == 0 else vv[:, None, :]
                t = t * vv
            ocx = t[..., 0] * pw + pcx
            ocy = t[..., 1] * ph + pcy
            ow = jnp.exp(t[..., 2]) * pw
            oh = jnp.exp(t[..., 3]) * ph
            return jnp.stack([ocx - ow * 0.5, ocy - oh * 0.5,
                              ocx + ow * 0.5 - (1.0 - norm),
                              ocy + oh * 0.5 - (1.0 - norm)], axis=-1)
        args = (pb, tb) + ((pbv,) if isinstance(pbv, Tensor) else ())
        return apply("box_decode", fn, args)
    raise ValueError(f"unknown code_type {code_type}")


# ---------------------------------------------------------------------------
# roi ops
# ---------------------------------------------------------------------------
def _bilinear(feat, y, x):
    """feat (C, H, W); y/x arbitrary same-shaped coords -> (C, *coords)."""
    c, h, w = feat.shape
    y0 = jnp.clip(jnp.floor(y), 0, h - 1)
    x0 = jnp.clip(jnp.floor(x), 0, w - 1)
    y1 = jnp.clip(y0 + 1, 0, h - 1)
    x1 = jnp.clip(x0 + 1, 0, w - 1)
    ly = jnp.clip(y - y0, 0.0, 1.0)
    lx = jnp.clip(x - x0, 0.0, 1.0)
    y0i, y1i = y0.astype(jnp.int32), y1.astype(jnp.int32)
    x0i, x1i = x0.astype(jnp.int32), x1.astype(jnp.int32)

    def g(yi, xi):
        return feat[:, yi, xi]                        # (C, *coords)

    out = (g(y0i, x0i) * ((1 - ly) * (1 - lx))
           + g(y0i, x1i) * ((1 - ly) * lx)
           + g(y1i, x0i) * (ly * (1 - lx))
           + g(y1i, x1i) * (ly * lx))
    # outside the feature map entirely -> 0 (reference convention)
    valid = (y > -1) & (y < h) & (x > -1) & (x < w)
    return jnp.where(valid[None], out, 0.0)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """≙ paddle.vision.ops.roi_align («paddle/phi/kernels/*/roi_align*»
    [U]). x (N, C, H, W); boxes (R, 4) xyxy; boxes_num (N,) ROIs per
    image."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    x_t, boxes_t, bn_t = _t(x), _t(boxes), _t(boxes_num)
    # static per-image box batch index (host-computed, like the reference's
    # lod/boxes_num handling)
    bn = np.asarray(bn_t._value)
    batch_idx = np.repeat(np.arange(bn.shape[0]), bn)

    def fn(feat, bxs):
        off = 0.5 if aligned else 0.0
        s = sampling_ratio if sampling_ratio > 0 else 2

        def one(b_idx, box):
            x1, y1, x2, y2 = (box * spatial_scale) - off
            rw = jnp.maximum(x2 - x1, 1e-10 if aligned else 1.0)
            rh = jnp.maximum(y2 - y1, 1e-10 if aligned else 1.0)
            bh, bw = rh / oh, rw / ow
            iy = (jnp.arange(oh)[:, None, None, None]
                  * jnp.ones((1, ow, s, s)))
            ix = (jnp.arange(ow)[None, :, None, None]
                  * jnp.ones((oh, 1, s, s)))
            sy = (jnp.arange(s)[None, None, :, None] + 0.5) / s
            sx = (jnp.arange(s)[None, None, None, :] + 0.5) / s
            yy = y1 + (iy + sy) * bh
            xx = x1 + (ix + sx) * bw
            vals = _bilinear(feat[b_idx], yy, xx)     # (C, oh, ow, s, s)
            return vals.mean(axis=(-1, -2))           # (C, oh, ow)

        return jax.vmap(one)(jnp.asarray(batch_idx), bxs)
    return apply("roi_align", fn, (x_t, boxes_t))


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
             name=None):
    """≙ paddle.vision.ops.roi_pool (max pooling per bin, quantized
    coords)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    x_t, boxes_t, bn_t = _t(x), _t(boxes), _t(boxes_num)
    bn = np.asarray(bn_t._value)
    batch_idx = np.repeat(np.arange(bn.shape[0]), bn)
    H, W = x_t.shape[2], x_t.shape[3]

    def fn(feat, bxs):
        def one(b_idx, box):
            x1 = jnp.round(box[0] * spatial_scale).astype(jnp.int32)
            y1 = jnp.round(box[1] * spatial_scale).astype(jnp.int32)
            x2 = jnp.round(box[2] * spatial_scale).astype(jnp.int32)
            y2 = jnp.round(box[3] * spatial_scale).astype(jnp.int32)
            rh = jnp.maximum(y2 - y1 + 1, 1)
            rw = jnp.maximum(x2 - x1 + 1, 1)
            ys = jnp.arange(H)
            xs = jnp.arange(W)

            def binmax(i, j):
                hstart = y1 + (i * rh) // oh
                hend = y1 + ((i + 1) * rh + oh - 1) // oh
                wstart = x1 + (j * rw) // ow
                wend = x1 + ((j + 1) * rw + ow - 1) // ow
                m = ((ys[:, None] >= hstart) & (ys[:, None] < hend)
                     & (xs[None, :] >= wstart) & (xs[None, :] < wend)
                     & (ys[:, None] < H) & (xs[None, :] < W))
                sel = jnp.where(m[None], feat[b_idx], -jnp.inf)
                out = sel.max(axis=(1, 2))
                return jnp.where(jnp.any(m), out, 0.0)
            ii = jnp.arange(oh)
            jj = jnp.arange(ow)
            grid = jax.vmap(lambda i: jax.vmap(
                lambda j: binmax(i, j))(jj))(ii)      # (oh, ow, C)
            return jnp.transpose(grid, (2, 0, 1))
        return jax.vmap(one)(jnp.asarray(batch_idx), bxs)
    return apply("roi_pool", fn, (x_t, boxes_t))


# ---------------------------------------------------------------------------
# deformable conv
# ---------------------------------------------------------------------------
def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """≙ paddle.vision.ops.deform_conv2d (DCNv1; DCNv2 when mask given).
    TPU design: bilinear-gather the deformed im2col patches, then one
    (N*OH*OW, C*KH*KW) @ (C*KH*KW, Cout) matmul on the MXU."""
    st = (stride, stride) if isinstance(stride, int) else tuple(stride)
    pd = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dl = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)
    x_t, off_t, w_t = _t(x), _t(offset), _t(weight)
    args = [x_t, off_t, w_t]
    if mask is not None:
        args.append(_t(mask))
    if bias is not None:
        args.append(_t(bias))
    has_mask = mask is not None
    has_bias = bias is not None

    def fn(xv, ov, wv, *rest):
        mv = rest[0] if has_mask else None
        bv = rest[-1] if has_bias else None
        n, c, h, w = xv.shape
        cout, cin_g, kh, kw = wv.shape
        oh = (h + 2 * pd[0] - dl[0] * (kh - 1) - 1) // st[0] + 1
        ow = (w + 2 * pd[1] - dl[1] * (kw - 1) - 1) // st[1] + 1
        dg = deformable_groups
        # offsets (N, 2*dg*kh*kw, OH, OW) in (dy, dx) pairs
        ov2 = ov.reshape(n, dg, kh * kw, 2, oh, ow)

        base_y = (jnp.arange(oh) * st[0] - pd[0])
        base_x = (jnp.arange(ow) * st[1] - pd[1])
        ky = jnp.arange(kh) * dl[0]
        kx = jnp.arange(kw) * dl[1]
        # sampling positions (dg, kh*kw, OH, OW)
        yy = (base_y[None, None, :, None]
              + ky.repeat(kw)[None, :, None, None]
              + ov2[:, :, :, 0])
        xx = (base_x[None, None, None, :]
              + jnp.tile(kx, kh)[None, :, None, None]
              + ov2[:, :, :, 1])

        cg = c // dg

        def per_image(feat, y_i, x_i, m_i):
            # feat (C,H,W); y_i/x_i (dg, khkw, OH, OW)
            def per_dg(fg, yg, xg):
                return _bilinear(fg, yg, xg)          # (cg, khkw, OH, OW)
            vals = jax.vmap(per_dg)(feat.reshape(dg, cg, h, w), y_i, x_i)
            vals = vals.reshape(c, kh * kw, oh, ow)
            if m_i is not None:
                vals = vals * m_i.reshape(dg, 1, kh * kw, oh, ow) \
                    .repeat(cg, axis=1).reshape(c, kh * kw, oh, ow)
            return vals

        ms = (mv.reshape(n, dg, kh * kw, oh, ow) if mv is not None
              else [None] * n)
        cols = jax.vmap(per_image)(xv, yy, xx,
                                   ms if mv is not None else None) \
            if mv is not None else jax.vmap(
                lambda f, a, b: per_image(f, a, b, None))(xv, yy, xx)
        # cols (N, C, khkw, OH, OW) -> (N*OH*OW, C*khkw) matmul
        cols = jnp.transpose(cols, (0, 3, 4, 1, 2)).reshape(
            n * oh * ow, c * kh * kw)
        wmat = wv.reshape(cout, cin_g * kh * kw)
        if groups == 1:
            out = cols @ wmat.T
        else:
            cols_g = cols.reshape(n * oh * ow, groups,
                                  cin_g * kh * kw)
            w_g = wmat.reshape(groups, cout // groups, cin_g * kh * kw)
            out = jnp.einsum("bgk,gok->bgo", cols_g, w_g).reshape(
                n * oh * ow, cout)
        out = out.reshape(n, oh, ow, cout).transpose(0, 3, 1, 2)
        if bv is not None:
            out = out + bv.reshape(1, cout, 1, 1)
        return out.astype(xv.dtype)
    return apply("deform_conv2d", fn, tuple(args))


# ---------------------------------------------------------------------------
# layer wrappers
# ---------------------------------------------------------------------------
from ..nn.layer.layers import Layer  # noqa: E402


class DeformConv2D(Layer):
    """≙ paddle.vision.ops.DeformConv2D."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        from ..nn import initializer as init
        fan_in = in_channels * ks[0] * ks[1]
        bound = 1.0 / np.sqrt(fan_in)
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, ks[0], ks[1]),
            default_initializer=init.Uniform(-bound, bound))
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                (out_channels,), is_bias=True,
                default_initializer=init.Uniform(-bound, bound))
        self._stride, self._padding, self._dilation = stride, padding, \
            dilation
        self._dg, self._groups = deformable_groups, groups

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             self._stride, self._padding, self._dilation,
                             self._dg, self._groups, mask)


class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._o, self._s = output_size, spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self._o, self._s)


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._o, self._s = output_size, spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._o, self._s)
