"""paddle_tpu.vision — transforms + datasets + model zoo (subset).
≙ reference «python/paddle/vision/» [U]. The DiT/SD3 north-star models live in
paddle_tpu.models; this module provides the torchvision-like utility surface."""
from . import datasets  # noqa: F401
from . import ops  # noqa: F401
from . import transforms  # noqa: F401
from .models import (ResNet, resnet18, resnet34, resnet50,  # noqa: F401
                     resnet101, resnet152, LeNet, AlexNet, alexnet, VGG,
                     vgg11, vgg13, vgg16, vgg19, MobileNetV1, MobileNetV2,
                     mobilenet_v1, mobilenet_v2, SqueezeNet, squeezenet1_0,
                     squeezenet1_1, DenseNet, densenet121, densenet201,
                     ShuffleNetV2, shufflenet_v2_x0_5, shufflenet_v2_x1_0,
                     shufflenet_v2_x1_5, shufflenet_v2_x2_0, GoogLeNet,
                     googlenet)
