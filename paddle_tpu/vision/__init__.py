"""paddle_tpu.vision — transforms + datasets + model zoo (subset).
≙ reference «python/paddle/vision/» [U]. The DiT/SD3 north-star models live in
paddle_tpu.models; this module provides the torchvision-like utility surface."""
from . import datasets  # noqa: F401
from . import ops  # noqa: F401
from . import transforms  # noqa: F401
from .models import ResNet, resnet18, resnet50  # noqa: F401
