"""Runtime telemetry: metrics registry, span tracing, Prom/JSONL export.

The measurement substrate for the production-serving north star —
process-local, stdlib-only, and a guaranteed no-op unless enabled:

    import paddle_tpu.observability as telemetry

    telemetry.enable()               # or PDT_TELEMETRY=1 in the env
    ...serve / train...
    snap = telemetry.snapshot()      # JSON-safe programmatic view
    print(telemetry.to_prometheus()) # text exposition for scrapers

Three modules:

* `registry` — typed Counter/Gauge/Histogram instruments (labels,
  fixed bucket boundaries, monotonic-clock timers) behind the global
  `REGISTRY`.
* `trace` — nestable `span()` / point `event()` -> structured JSONL
  into a bounded ring buffer + optional file sink
  (`PDT_TELEMETRY_TRACE_FILE=`), interoperating with
  `profiler.RecordEvent` so spans land in the XLA timeline too. PLUS
  request-scoped distributed traces: `start_trace(request_id)` opens a
  trace whose carrier any span/event carrying that `request_id` attr
  joins automatically (router -> replica -> engine), `request_tree()`
  rebuilds one request's causal tree, and `export_chrome_trace()`
  renders Perfetto/chrome://tracing JSON (pid=replica, tid=request).
* `export` — Prometheus text exposition + JSON snapshot, with a
  `parse_prometheus()` round-trip verifier and an offline
  `render_prometheus(snapshot)` for saved snapshots.
* `slo` — streaming quantiles (le-bucket interpolation + an exact
  windowed reservoir) and the `SloMonitor` grading declarative
  objectives (TTFT/TPOT percentiles, error rate, availability) into
  pass/warn/breach with burn rates, exported as `pdt_slo_*` gauges.
* `profile` — the performance attribution plane: decode-round
  decomposition (`note_round`), the dispatch-gap sampler
  (`gap_sampler`/`fence`, driven by `engine.profile_round()`),
  compile-cache observability (`compile_timed` behind the engine's
  `_jit_lru`/`_jit_singleton` seam + the retrace-storm detector), the
  `pdt_mem_bytes{pool}` memory ledger, and
  `render_profile_report(snapshot)` for the waterfall / top-gap /
  compile-table / ledger text report.
* `status` — `render_fleet_status()`: the human-readable fleet report.
* `__main__` — the operator CLI (`python -m paddle_tpu.observability
  snapshot|slo|trace ...`, installed as `paddle-tpu-obs`).

Instrumented out of the box: the continuous-batching engine (TTFT,
time-per-output-token, tokens/sec, queue depth, admissions/rejections,
preemptions, page occupancy, terminal-status counters, invariant-check
duration), `generate()` compile/dispatch, fault-injection fires,
elastic launcher restarts + heartbeat staleness, checkpoint save/load
spans + bytes, and checkpoint durability (save retries, quarantines,
resume fallback depth, verify duration — docs/checkpointing.md).
Metric catalog: docs/serving.md "Observability".
"""
from __future__ import annotations

from .registry import (DEFAULT_BUCKETS, REGISTRY, Counter, Gauge,  # noqa: F401
                       Histogram, Registry, counter, disable, enable,
                       enabled, gauge, histogram, reset, snapshot, value)
from .trace import (clear as clear_events, event, events,  # noqa: F401
                    set_trace_file, span, trace_file, start_trace,
                    end_trace, trace_of, attach as trace_attach,
                    request_tree, export_chrome_trace,
                    load_trace_jsonl)
from .export import (parse_prometheus, render_prometheus,  # noqa: F401
                     to_json, to_prometheus, write_json)
from .slo import (Reservoir, SloMonitor, SloObjective,  # noqa: F401
                  SloStatus, default_serving_objectives,
                  evaluate_snapshot, format_slo_report,
                  objectives_from_spec, quantile_from_buckets)
from .status import render_fleet_status  # noqa: F401
from . import profile  # noqa: F401
from .profile import (memory_ledger, note_round,  # noqa: F401
                      render_profile_report, snapshot_report)

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "REGISTRY",
    "DEFAULT_BUCKETS", "counter", "gauge", "histogram",
    "enable", "disable", "enabled", "reset", "snapshot", "value",
    "span", "event", "events", "clear_events", "set_trace_file",
    "trace_file", "start_trace", "end_trace", "trace_of",
    "trace_attach", "request_tree", "export_chrome_trace",
    "load_trace_jsonl", "to_prometheus", "render_prometheus",
    "to_json", "write_json", "parse_prometheus",
    "Reservoir", "SloMonitor", "SloObjective", "SloStatus",
    "default_serving_objectives", "evaluate_snapshot",
    "format_slo_report", "objectives_from_spec",
    "quantile_from_buckets", "render_fleet_status",
    "profile", "memory_ledger", "note_round",
    "render_profile_report", "snapshot_report",
]
