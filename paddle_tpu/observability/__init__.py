"""Runtime telemetry: metrics registry, span tracing, Prom/JSONL export.

The measurement substrate for the production-serving north star —
process-local, stdlib-only, and a guaranteed no-op unless enabled:

    import paddle_tpu.observability as telemetry

    telemetry.enable()               # or PDT_TELEMETRY=1 in the env
    ...serve / train...
    snap = telemetry.snapshot()      # JSON-safe programmatic view
    print(telemetry.to_prometheus()) # text exposition for scrapers

Three modules:

* `registry` — typed Counter/Gauge/Histogram instruments (labels,
  fixed bucket boundaries, monotonic-clock timers) behind the global
  `REGISTRY`.
* `trace` — nestable `span()` / point `event()` -> structured JSONL
  into a bounded ring buffer + optional file sink
  (`PDT_TELEMETRY_TRACE_FILE=`), interoperating with
  `profiler.RecordEvent` so spans land in the XLA timeline too.
* `export` — Prometheus text exposition + JSON snapshot, with a
  `parse_prometheus()` round-trip verifier.

Instrumented out of the box: the continuous-batching engine (TTFT,
time-per-output-token, tokens/sec, queue depth, admissions/rejections,
preemptions, page occupancy, terminal-status counters, invariant-check
duration), `generate()` compile/dispatch, fault-injection fires,
elastic launcher restarts + heartbeat staleness, checkpoint save/load
spans + bytes, and checkpoint durability (save retries, quarantines,
resume fallback depth, verify duration — docs/checkpointing.md).
Metric catalog: docs/serving.md "Observability".
"""
from __future__ import annotations

from .registry import (DEFAULT_BUCKETS, REGISTRY, Counter, Gauge,  # noqa: F401
                       Histogram, Registry, counter, disable, enable,
                       enabled, gauge, histogram, reset, snapshot, value)
from .trace import (clear as clear_events, event, events,  # noqa: F401
                    set_trace_file, span, trace_file)
from .export import (parse_prometheus, to_json, to_prometheus,  # noqa: F401
                     write_json)

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "REGISTRY",
    "DEFAULT_BUCKETS", "counter", "gauge", "histogram",
    "enable", "disable", "enabled", "reset", "snapshot", "value",
    "span", "event", "events", "clear_events", "set_trace_file",
    "trace_file", "to_prometheus", "to_json", "write_json",
    "parse_prometheus",
]
