"""Operator CLI for the observability subsystem.

    python -m paddle_tpu.observability snapshot [--from FILE]
        [--format prom|json] [--out FILE]
    python -m paddle_tpu.observability slo --from SNAP.json
        [--spec SPEC.json] [--warn-burn 0.5]
    python -m paddle_tpu.observability trace export IN.jsonl
        --chrome OUT.json
    python -m paddle_tpu.observability trace tree IN.jsonl
        --request REQUEST_ID
    python -m paddle_tpu.observability status --from FLEET.json
    python -m paddle_tpu.observability profile --from SNAP.json
        [--top-gaps 10]

`profile` renders the performance-attribution report from a saved
metrics snapshot (JSON or Prometheus text): the decode-round
decomposition waterfall, the ranked `pdt_profile_gap_seconds` table
from the last `engine.profile_round()`, the per-family compile-cache
table, and the `pdt_mem_bytes{pool}` memory ledger — exits non-zero
when the snapshot carries no profile series at all.
`status` renders a saved `ServingRouter.fleet_info()` snapshot as the
operator report (per-replica role + health, role aggregates,
prefix-store stats, SLO verdicts — status.render_fleet_status).
`snapshot` converts between the two export forms: load a saved JSON
snapshot (`telemetry.write_json`) or a Prometheus text dump
(`.prom` / `.txt`, parsed with `parse_prometheus`) and render it as
either form — without `--from` it dumps THIS process's registry (empty
in a fresh CLI process; useful mainly under `PDT_TELEMETRY=1` in an
embedding). `slo` grades objectives (the JSON spec format of
docs/observability.md, defaulting to the stock serving set) against a
saved snapshot and exits non-zero when any objective is in breach.
`trace export` converts a JSONL trace sink into Chrome trace-event
JSON loadable by chrome://tracing and Perfetto (pid=replica,
tid=request); `trace tree` prints one request's reconstructed span
tree. Installed as `paddle-tpu-obs`.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from . import export as _export
from . import slo as _slo
from . import trace as _trace

__all__ = ["main"]


def _load_snapshot(path: str) -> dict:
    with open(path) as f:
        text = f.read()
    try:
        snap = json.loads(text)
    except json.JSONDecodeError:
        snap = _export.parse_prometheus(text)
    if not isinstance(snap, dict):
        raise SystemExit(f"{path}: not a snapshot (JSON object or "
                         "Prometheus text exposition expected)")
    for key in ("counters", "gauges", "histograms"):
        snap.setdefault(key, {})
    return snap


def _write(text: str, out: Optional[str]):
    if out is None:
        sys.stdout.write(text if text.endswith("\n") or not text
                         else text + "\n")
    else:
        with open(out, "w") as f:
            f.write(text if text.endswith("\n") or not text
                    else text + "\n")


def _cmd_snapshot(args) -> int:
    if args.src is not None:
        snap = _load_snapshot(args.src)
    else:
        from .registry import snapshot
        snap = snapshot()
    if args.format == "json":
        _write(json.dumps(snap, indent=2, sort_keys=True), args.out)
    else:
        _write(_export.render_prometheus(snap), args.out)
    return 0


def _cmd_slo(args) -> int:
    snap = _load_snapshot(args.src)
    objectives = (_slo.objectives_from_spec(args.spec)
                  if args.spec else None)
    statuses = _slo.evaluate_snapshot(snap, objectives,
                                      warn_burn=args.warn_burn)
    print(_slo.format_slo_report(statuses, warn_burn=args.warn_burn))
    return 1 if any(not st.ok for st in statuses.values()) else 0


def _cmd_status(args) -> int:
    from .status import render_fleet_status
    with open(args.src) as f:
        info = json.load(f)
    if not isinstance(info, dict) or "replicas" not in info:
        raise SystemExit(f"{args.src}: not a fleet_info() snapshot "
                         "(JSON object with a 'replicas' list "
                         "expected)")
    print(render_fleet_status(info))
    return 0


def _cmd_profile(args) -> int:
    from . import profile as _profile
    snap = _load_snapshot(args.src)
    report = _profile.render_profile_report(snap,
                                            top_gaps=args.top_gaps)
    print(report)
    # mirror `slo`'s exit-code contract: non-zero when there is
    # nothing to attribute (no pdt_profile_*/pdt_jit_*/pdt_mem_*
    # series in the snapshot at all)
    empty = not (_profile.round_summary(snap)
                 or _profile.gap_table(snap)
                 or _profile.compile_summary(snap)
                 or _profile.mem_summary(snap))
    return 1 if empty else 0


def _cmd_trace_export(args) -> int:
    evts = _trace.load_trace_jsonl(args.jsonl)
    doc = _trace.export_chrome_trace(evts, path=args.chrome)
    spans = sum(1 for e in doc["traceEvents"] if e.get("ph") == "X")
    print(f"{args.chrome}: {len(doc['traceEvents'])} trace events "
          f"({spans} spans) from {len(evts)} JSONL records — load in "
          "chrome://tracing or https://ui.perfetto.dev")
    return 0


def _cmd_trace_tree(args) -> int:
    evts = _trace.load_trace_jsonl(args.jsonl)
    tree = _trace.request_tree(args.request, evts)
    if tree is None:
        print(f"no trace root for request {args.request!r} in "
              f"{args.jsonl}", file=sys.stderr)
        return 1
    print(_trace.format_tree(tree))
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability",
        description="Operator surface: snapshots, SLO reports, traces.")
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("snapshot",
                       help="dump/convert a metrics snapshot")
    s.add_argument("--from", dest="src", metavar="FILE", default=None,
                   help="saved JSON snapshot or Prometheus text "
                        "(default: this process's registry)")
    s.add_argument("--format", choices=("prom", "json"), default="prom")
    s.add_argument("--out", metavar="FILE", default=None,
                   help="write here instead of stdout")
    s.set_defaults(fn=_cmd_snapshot)

    s = sub.add_parser("slo", help="grade SLO objectives against a "
                                   "saved snapshot")
    s.add_argument("--from", dest="src", metavar="SNAP.json",
                   required=True)
    s.add_argument("--spec", metavar="SPEC.json", default=None,
                   help="objective spec (default: the stock serving "
                        "objectives)")
    s.add_argument("--warn-burn", type=float, default=0.5)
    s.set_defaults(fn=_cmd_slo)

    s = sub.add_parser("status", help="render a saved fleet_info() "
                                      "snapshot (roles, SLO, prefix "
                                      "store)")
    s.add_argument("--from", dest="src", metavar="FLEET.json",
                   required=True)
    s.set_defaults(fn=_cmd_status)

    s = sub.add_parser("profile", help="render the performance-"
                                       "attribution report from a "
                                       "saved snapshot")
    s.add_argument("--from", dest="src", metavar="SNAP.json",
                   required=True,
                   help="saved JSON snapshot or Prometheus text")
    s.add_argument("--top-gaps", type=int, default=10,
                   help="rows in the dispatch-gap table (default 10)")
    s.set_defaults(fn=_cmd_profile)

    t = sub.add_parser("trace", help="trace tooling")
    tsub = t.add_subparsers(dest="trace_cmd", required=True)
    s = tsub.add_parser("export", help="JSONL -> Chrome trace JSON")
    s.add_argument("jsonl")
    s.add_argument("--chrome", metavar="OUT.json", required=True)
    s.set_defaults(fn=_cmd_trace_export)
    s = tsub.add_parser("tree", help="print one request's span tree")
    s.add_argument("jsonl")
    s.add_argument("--request", required=True)
    s.set_defaults(fn=_cmd_trace_tree)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
