"""Performance attribution: decode-round decomposition, compile-cache
observability, the dispatch-gap sampler, and the memory ledger.

ROADMAP item 1 (the megakernel decode fusion ladder) deletes host
dispatch gaps between the RMSNorm -> QKV -> RoPE -> ragged-attention ->
MLP ops of a decode round — this module is how those gaps are MEASURED,
so each rung is chosen by ranked evidence and graded by the same
instrument. Four surfaces, all in the PR-2 tradition (stdlib+jax only,
guaranteed no-op unless telemetry is enabled):

* **Decode-round decomposition** — the engine threads `note_round()`
  through `step()`/`_decode`/`_harvest_*` (and the router through its
  journal mirror), splitting each round's wall into
  dispatch / device / harvest / journal / sentry / host components
  (`pdt_profile_round_seconds{component}`). The components are measured
  wall intervals, so their sums reconcile against an independently
  timed round (test-pinned to 10%).
* **Dispatch-gap sampler** — `gap_sampler()` + the `fence()` hooks in
  `models/llama.py`: `ContinuousBatchingEngine.profile_round()` runs
  ONE un-jitted decode round with `jax.block_until_ready` fences at
  every op-family boundary, attributing host time between fences as
  the dispatch gap of that op pair (`pdt_profile_gap_seconds{op_pair}`,
  ranked by `gap_table()` — the fusion ladder's shopping list). The
  sampled round is purely functional: outputs are discarded, engine
  state and the PRNG stream are untouched, so the served token stream
  stays bit-identical.
* **Compile-cache observability** — `compile_timed()` wraps every
  program the engine's `_jit_lru`/`_jit_singleton` seam builds: the
  first invocation (the one that traces and compiles) is metered as
  `pdt_jit_compiles_total{family}` + `pdt_jit_compile_seconds` under a
  `jit.compile` span, cache footprints ride
  `pdt_jit_cache_entries{family}` / `pdt_jit_cache_evictions_total`,
  and a sliding-window retrace-storm detector emits the
  `profile.retrace_storm` event (+ `pdt_jit_retrace_storms_total`)
  when program-key churn drives compiles past a threshold — the
  failure mode the pow2 bucketing exists to prevent, now detectable.
* **Memory ledger** — `memory_ledger()` folds `cache_memory_info`,
  draft pools, prefix-store spill bytes, and model-store residency
  into the one `pdt_mem_bytes{pool}` family, surfaced by
  `fleet_info()["perf"]` and `render_fleet_status`.

`render_profile_report(snapshot)` renders all four surfaces from any
saved snapshot — the `paddle-tpu-obs profile` CLI, the post-kill-drill
report in `recipes/llama_serve.py`, and failing-test attachments in
`tests/conftest.py` all print the same text.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Callable, Dict, List, Optional

from . import registry as _registry
from . import trace as _trace
from .registry import counter, gauge, histogram

__all__ = ["COMPONENTS", "note_round", "compile_timed", "note_cache",
           "configure_retrace", "retrace_window", "gap_sampler",
           "fence", "gap_table", "memory_ledger", "perf_section",
           "round_summary", "compile_summary", "mem_summary",
           "render_profile_report", "snapshot_report"]

# the decode-round attribution axes (see module docstring); "host" is
# the expiry/admission/bookkeeping remainder the engine meters itself
COMPONENTS = ("dispatch", "device", "harvest", "journal", "sentry",
              "host")

# round walls are sub-ms host slices up to multi-second cold dispatches
_ROUND_BUCKETS = (1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 0.001,
                  0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                  1.0, 2.5, 5.0)

_M_ROUND = histogram(
    "pdt_profile_round_seconds",
    "Wall seconds of one decode-round component (the engine/router "
    "attribution hooks), by component.", ("component",),
    buckets=_ROUND_BUCKETS)
_M_GAP = gauge(
    "pdt_profile_gap_seconds",
    "Host dispatch gap between two op families summed over the most "
    "recently gap-sampled decode round (profile_round), by op pair — "
    "the megakernel fusion ladder's ranked shopping list.", ("op_pair",))
_M_JIT_COMPILES = counter(
    "pdt_jit_compiles_total",
    "Programs compiled through the _jit_lru/_jit_singleton seam "
    "(first invocation of a freshly built jit), by program family.",
    ("family",))
_M_JIT_COMPILE_SECONDS = histogram(
    "pdt_jit_compile_seconds",
    "Wall seconds of a program's first invocation — trace + compile + "
    "first execute, the honest cold-start bill.", ("family",))
_M_JIT_CACHE = gauge(
    "pdt_jit_cache_entries",
    "Programs resident in a keyed-LRU jit cache, by family.",
    ("family",))
_M_JIT_EVICTIONS = counter(
    "pdt_jit_cache_evictions_total",
    "Programs evicted from a keyed-LRU jit cache past its cap, by "
    "family.", ("family",))
_M_RETRACE_STORMS = counter(
    "pdt_jit_retrace_storms_total",
    "Retrace-storm detections: sliding-window compile count exceeded "
    "the storm threshold (program-key churn).")
_M_MEM = gauge(
    "pdt_mem_bytes",
    "Memory ledger: bytes held per accounting pool (KV pools, draft "
    "pools, prefix-store spill, model-store residency).", ("pool",))


def note_round(component: str, seconds: float) -> None:
    """Record one decode-round component wall interval. No-op unless
    telemetry is enabled (the Histogram gate)."""
    _M_ROUND.observe(seconds, component=component)


# -- compile-cache observability --------------------------------------

class _RetraceWindow:
    """Sliding-window compile counter: a storm is >= `threshold`
    compiles inside `window_s` seconds. The clock is injectable for
    tests; detection is re-armed only after the window drains below
    half the threshold, so one sustained churn episode fires once per
    window rather than once per compile."""

    def __init__(self, window_s: float = 30.0, threshold: int = 10,
                 clock: Callable[[], float] = time.monotonic):
        self.window_s = float(window_s)
        self.threshold = int(threshold)
        self.clock = clock
        self._times: deque = deque()
        self._families: deque = deque()
        self._armed = True

    def note(self, family: str) -> bool:
        """Record one compile; True when this compile tripped a storm."""
        now = self.clock()
        self._times.append(now)
        self._families.append(family)
        while self._times and now - self._times[0] > self.window_s:
            self._times.popleft()
            self._families.popleft()
        n = len(self._times)
        if n < self.threshold:
            if n <= self.threshold // 2:
                self._armed = True
            return False
        if not self._armed:
            return False
        self._armed = False
        fams: Dict[str, int] = {}
        for f in self._families:
            fams[f] = fams.get(f, 0) + 1
        _M_RETRACE_STORMS.inc()
        _trace.event("profile.retrace_storm", compiles=n,
                     window_s=self.window_s,
                     threshold=self.threshold,
                     families=",".join(f"{k}={v}"
                                       for k, v in sorted(fams.items())))
        return True

    def count(self) -> int:
        now = self.clock()
        while self._times and now - self._times[0] > self.window_s:
            self._times.popleft()
            self._families.popleft()
        return len(self._times)


_RETRACE = _RetraceWindow()


def retrace_window() -> _RetraceWindow:
    return _RETRACE


def configure_retrace(window_s: Optional[float] = None,
                      threshold: Optional[int] = None,
                      clock: Optional[Callable[[], float]] = None) \
        -> _RetraceWindow:
    """Replace the process-wide retrace-storm detector (tests inject a
    fake clock / low threshold; returns the new window)."""
    global _RETRACE
    cur = _RETRACE
    _RETRACE = _RetraceWindow(
        window_s=cur.window_s if window_s is None else window_s,
        threshold=cur.threshold if threshold is None else threshold,
        clock=cur.clock if clock is None else clock)
    return _RETRACE


def compile_timed(fn, family: str, key=None):
    """Wrap a freshly built (never-invoked) ``jax.jit`` callable so its
    FIRST invocation — the one that traces and compiles — is metered:
    `pdt_jit_compiles_total{family}` / `pdt_jit_compile_seconds` under
    a `jit.compile` span, feeding the retrace-storm window. Later
    invocations pay one boolean check. The engine's `_jit_lru` /
    `_jit_singleton` seam routes every cached program through here
    (pdt-lint PDT012 pins that), so compile observability cannot be
    bypassed."""
    state = [True]

    def _first_call_timed(*args, **kwargs):
        if not state[0]:
            return fn(*args, **kwargs)
        state[0] = False
        if not _registry.enabled():
            return fn(*args, **kwargs)
        t0 = time.perf_counter()
        with _trace.span("jit.compile", family=family,
                         key="" if key is None else str(key)):
            out = fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        _M_JIT_COMPILES.inc(family=family)
        _M_JIT_COMPILE_SECONDS.observe(dt, family=family)
        _RETRACE.note(family)
        return out

    return _first_call_timed


def note_cache(family: str, entries: int, evicted: int = 0) -> None:
    """Record a keyed-LRU cache's footprint after a miss/evict pass."""
    if not _registry.enabled():
        return
    _M_JIT_CACHE.set(entries, family=family)
    if evicted:
        _M_JIT_EVICTIONS.inc(evicted, family=family)


# -- dispatch-gap sampler ---------------------------------------------

class _GapSampler:
    """Collects (op, dispatch_done_t, fence_done_t) triples from the
    `fence()` hooks of ONE un-jitted decode round. The gap of pair
    A->B is the host wall between A's fence completing (device idle)
    and B's ops all being enqueued — the dispatch overhead a fused
    kernel would delete. `device_s` is B's fence wait, i.e. its device
    compute (plus copy) once enqueued."""

    def __init__(self):
        self._events: List = []    # (op, t_dispatched, t_done)

    def note(self, op: str, t_dispatched: float, t_done: float):
        self._events.append((op, t_dispatched, t_done))

    def table(self) -> List[Dict[str, object]]:
        pairs: Dict[str, Dict[str, float]] = {}
        prev_op, prev_done = None, None
        for op, t_disp, t_done in self._events:
            if prev_op is not None:
                row = pairs.setdefault(
                    f"{prev_op}->{op}",
                    {"gap_s": 0.0, "device_s": 0.0, "count": 0})
                row["gap_s"] += max(t_disp - prev_done, 0.0)
                row["device_s"] += t_done - t_disp
                row["count"] += 1
            prev_op, prev_done = op, t_done
        out = [{"op_pair": k, **v} for k, v in pairs.items()]
        out.sort(key=lambda r: -r["gap_s"])
        for row in out:
            _M_GAP.set(row["gap_s"], op_pair=row["op_pair"])
        return out


_SAMPLER: Optional[_GapSampler] = None


class gap_sampler:
    """Context manager arming the op-family fences for one sampled
    round. Enter returns the sampler; call `.table()` after the round
    for the ranked gap table (it also publishes the
    `pdt_profile_gap_seconds{op_pair}` gauges)."""

    def __enter__(self) -> _GapSampler:
        global _SAMPLER
        self._sampler = _GapSampler()
        _SAMPLER = self._sampler
        return self._sampler

    def __exit__(self, *exc):
        global _SAMPLER
        _SAMPLER = None
        return False


def fence(op: str, value):
    """Op-family boundary hook (models/llama.py threads these through
    the ragged decode path): inert — one global check — unless a
    `gap_sampler()` is armed, in which case the value is
    block_until_ready-fenced and the (dispatch-done, fence-done) pair
    recorded. Returns `value` unchanged either way, so the hook is
    transparent under jit tracing."""
    s = _SAMPLER
    if s is None:
        return value
    import jax
    t_disp = time.perf_counter()
    leaves = value if isinstance(value, (tuple, list)) else (value,)
    for leaf in leaves:
        jax.block_until_ready(getattr(leaf, "_value", leaf))
    s.note(op, t_disp, time.perf_counter())
    return value


def gap_table(snapshot: Dict[str, object]) -> List[Dict[str, object]]:
    """Ranked dispatch-gap rows from a saved snapshot's
    `pdt_profile_gap_seconds` gauges."""
    series = snapshot.get("gauges", {}).get("pdt_profile_gap_seconds",
                                            {})
    rows = []
    for labels, v in series.items():
        # labels: op_pair="a->b"
        pair = labels.split('"')[1] if '"' in labels else labels
        rows.append({"op_pair": pair, "gap_s": float(v)})
    rows.sort(key=lambda r: -r["gap_s"])
    return rows


# -- memory ledger -----------------------------------------------------

def _engine_pools(engine) -> Dict[str, float]:
    pools = {"kv_pool": 0.0, "kv_in_use": 0.0}
    info = engine.cache_memory_info()
    pools["kv_pool"] += float(info.get("bytes_pool", 0))
    pools["kv_in_use"] += float(info.get("bytes_in_use", 0))
    d_kv = getattr(engine, "_d_kv", None)
    if d_kv:
        pools["draft_pool"] = float(sum(
            sum(int(arr.nbytes) for arr in entry) for entry in d_kv))
    return pools


def memory_ledger(engines=(), prefix_store=None,
                  model_store=None) -> Dict[str, float]:
    """Fold the fleet's memory accounting into the one
    `pdt_mem_bytes{pool}` family (gauges set as a side effect when
    telemetry is on) and return the pool -> bytes dict."""
    pools: Dict[str, float] = {}
    for eng in engines:
        if eng is None:
            continue
        for name, v in _engine_pools(eng).items():
            pools[name] = pools.get(name, 0.0) + v
    if prefix_store is not None:
        pools["prefix_spill"] = float(
            prefix_store.stats().get("spilled_bytes", 0))
    if model_store is not None:
        resident = model_store.stats().get("resident_bytes", {})
        pools["model_store"] = float(sum(resident.values()))
    for name, v in pools.items():
        _M_MEM.set(v, pool=name)
    return pools


def perf_section(engines=(), prefix_store=None,
                 model_store=None) -> Dict[str, object]:
    """The `fleet_info()["perf"]` section: the memory ledger plus the
    compile-cache counters, read from the live registry (zeros when
    telemetry is off — the ledger itself is computed either way)."""
    mem = memory_ledger(engines, prefix_store=prefix_store,
                        model_store=model_store)
    jit: Dict[str, Dict[str, float]] = {}
    for fam_series, key in ((_M_JIT_COMPILES, "compiles"),
                            (_M_JIT_CACHE, "entries"),
                            (_M_JIT_EVICTIONS, "evictions")):
        for labels, v in fam_series._series.items():
            fam = labels[0] if labels else ""
            jit.setdefault(fam, {})[key] = float(v)
    return {"mem_bytes": mem, "jit": jit,
            "retrace_storms": _M_RETRACE_STORMS.get()}


# -- snapshot report rendering ----------------------------------------

def _label_value(labels: str) -> str:
    return labels.split('"')[1] if '"' in labels else labels


def round_summary(snapshot: Dict[str, object]) -> Dict[str, dict]:
    """component -> {count, total_s, median_s} from a snapshot's
    `pdt_profile_round_seconds` series."""
    from .slo import quantile_from_buckets
    out: Dict[str, dict] = {}
    series = snapshot.get("histograms", {}).get(
        "pdt_profile_round_seconds", {})
    for labels, s in series.items():
        if not s.get("count"):
            continue
        med = quantile_from_buckets(s["buckets"], 0.5)
        out[_label_value(labels)] = {
            "count": int(s["count"]), "total_s": float(s["sum"]),
            "median_s": float(med) if med is not None else None}
    return out


def compile_summary(snapshot: Dict[str, object]) -> Dict[str, dict]:
    """family -> {compiles, compile_s, entries, evictions}."""
    out: Dict[str, dict] = {}
    for labels, v in snapshot.get("counters", {}).get(
            "pdt_jit_compiles_total", {}).items():
        out.setdefault(_label_value(labels), {})["compiles"] = int(v)
    for labels, s in snapshot.get("histograms", {}).get(
            "pdt_jit_compile_seconds", {}).items():
        out.setdefault(_label_value(labels), {})["compile_s"] = \
            float(s.get("sum", 0.0))
    for labels, v in snapshot.get("gauges", {}).get(
            "pdt_jit_cache_entries", {}).items():
        out.setdefault(_label_value(labels), {})["entries"] = int(v)
    for labels, v in snapshot.get("counters", {}).get(
            "pdt_jit_cache_evictions_total", {}).items():
        out.setdefault(_label_value(labels), {})["evictions"] = int(v)
    return out


def mem_summary(snapshot: Dict[str, object]) -> Dict[str, float]:
    return {_label_value(labels): float(v)
            for labels, v in snapshot.get("gauges", {}).get(
                "pdt_mem_bytes", {}).items()}


def _fmt_s(v: Optional[float]) -> str:
    if v is None:
        return "-"
    if v >= 0.1:
        return f"{v:.3f}s"
    return f"{v * 1e3:.3f}ms"


def _fmt_bytes(v: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(v) < 1024 or unit == "GiB":
            return f"{v:.1f}{unit}" if unit != "B" else f"{int(v)}B"
        v /= 1024.0
    return f"{v:.1f}GiB"


def render_profile_report(snapshot: Dict[str, object],
                          top_gaps: int = 10) -> str:
    """The one profile report (waterfall + top gaps + compile table +
    memory ledger) from any saved snapshot — shared by the
    `paddle-tpu-obs profile` CLI, the recipes, and failing-test
    attachments. Sections with no data are omitted; an entirely empty
    report renders a one-line notice."""
    lines: List[str] = []
    rounds = round_summary(snapshot)
    if rounds:
        lines.append("decode-round decomposition")
        total = sum(r["total_s"] for r in rounds.values())
        order = [c for c in COMPONENTS if c in rounds] \
            + sorted(set(rounds) - set(COMPONENTS))
        for comp in order:
            r = rounds[comp]
            share = 100.0 * r["total_s"] / total if total > 0 else 0.0
            bar = "#" * max(int(round(share / 4)), 1)
            lines.append(
                f"  {comp:<9} median {_fmt_s(r['median_s']):>9}  "
                f"total {_fmt_s(r['total_s']):>9} ({share:5.1f}%) "
                f"{bar}")
    gaps = gap_table(snapshot)
    if gaps:
        lines.append("top dispatch gaps (last sampled round)")
        for row in gaps[:top_gaps]:
            lines.append(f"  {row['op_pair']:<28} "
                         f"{_fmt_s(row['gap_s']):>9}")
    compiles = compile_summary(snapshot)
    if compiles:
        lines.append("compile cache")
        lines.append(f"  {'family':<14} {'compiles':>8} "
                     f"{'compile_s':>10} {'entries':>8} {'evicted':>8}")
        for fam in sorted(compiles):
            c = compiles[fam]
            lines.append(
                f"  {fam:<14} {c.get('compiles', 0):>8} "
                f"{c.get('compile_s', 0.0):>10.3f} "
                f"{c.get('entries', 0):>8} {c.get('evictions', 0):>8}")
        storms = snapshot.get("counters", {}).get(
            "pdt_jit_retrace_storms_total", {}).get("")
        if storms:
            lines.append(f"  retrace storms: {int(storms)}")
    mem = mem_summary(snapshot)
    if mem:
        lines.append("memory ledger")
        for pool in sorted(mem):
            lines.append(f"  {pool:<14} {_fmt_bytes(mem[pool]):>12}")
    if not lines:
        return ("no profile data in snapshot (pdt_profile_*/pdt_jit_*/"
                "pdt_mem_* series absent)")
    return "\n".join(lines)


def snapshot_report(top_gaps: int = 10) -> str:
    """`render_profile_report` of the LIVE registry."""
    return render_profile_report(_registry.snapshot(),
                                 top_gaps=top_gaps)
