"""Human-readable fleet status: the operator's one-glance surface.

`render_fleet_status` turns `ServingRouter.fleet_info()` (per-replica
role + health, queue depths, restart counts, the prefix-cache
aggregate, role aggregates + prefix-store stats for disaggregated
fleets, QoS admission state — lane admit/shed counts, tenant budget
occupancy, the arbitration burn — when a `QosAdmission` is attached,
and — when an `SloMonitor` is attached — per-replica and
fleet-level SLO verdicts, plus the performance-attribution surface of
`fleet_info()["perf"]`: the `pdt_mem_bytes{pool}` memory ledger and the
per-family jit compile-cache table) into the fixed-width report
`recipes/llama_serve.py` prints after its drills; `paddle-tpu-obs
status --from fleet.json` renders a saved snapshot. Pure formatting: no registry reads, no side effects,
so it can render a `fleet_info()` dict captured anywhere (a log line, a
post-mortem dump, a test)."""
from __future__ import annotations

from typing import Dict, List, Optional

__all__ = ["render_fleet_status"]


def _fmt_bytes(n: float) -> str:
    """`1536 -> 1.5KiB` — compact fixed-point byte counts for the
    memory-ledger line (the raw integers live in `pdt_mem_bytes`)."""
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024.0 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}GiB"


def _submesh_cell(sm: Optional[Dict[str, object]]) -> str:
    """`tp=2@[0,1]` — the replica's tensor-parallel placement (its
    GSPMD submesh shape + device ids), or `-` for single-chip."""
    if not sm:
        return "-"
    devs = ",".join(str(d) for d in sm.get("devices", []))
    return f"tp={sm.get('tp')}@[{devs}]"


def render_fleet_status(info: Dict[str, object]) -> str:
    """Format one `ServingRouter.fleet_info()` snapshot."""
    lines: List[str] = ["fleet status"]
    # the submesh column appears only for TP fleets — a single-chip
    # fleet's table stays byte-identical to what operators already read
    replicas = info.get("replicas", [])
    with_tp = any(r.get("submesh") for r in replicas)
    # width follows the widest cell: tp=4@[0,1,2,3] must not push the
    # slo/note columns out of line with the header
    tp_w = max([7] + [len(_submesh_cell(r.get("submesh")))
                      for r in replicas]) if with_tp else 0
    tp_hdr = f" {'submesh':<{tp_w}}" if with_tp else ""
    # width fits the gray-failure states too ("quarantined" = 11)
    st_w = max([5] + [len(str(r.get("state", ""))) for r in replicas])
    lines.append(f"  {'replica':<8} {'role':<10} {'state':<{st_w}} "
                 f"{'outstanding':>11} {'restarts':>8}{tp_hdr} "
                 f"{'slo':<7} note")
    for r in replicas:
        slo = r.get("slo")
        note = r.get("death_reason") or ""
        if r.get("consecutive_failures"):
            note = (note + " " if note else "") \
                + f"{r['consecutive_failures']} consecutive failures"
        tp_cell = f" {_submesh_cell(r.get('submesh')):<{tp_w}}" \
            if with_tp else ""
        lines.append(
            f"  {r['index']:<8} {r.get('role', 'colocated'):<10} "
            f"{r['state']:<{st_w}} "
            f"{r['outstanding']:>11} {r['restarts']:>8}{tp_cell} "
            f"{(slo.upper() if slo else '-'):<7} {note}".rstrip())
    lines.append(
        f"  requests: {info.get('submitted', 0)} submitted, "
        f"{info.get('pending', 0)} pending; "
        f"failovers {info.get('failovers', 0)}, "
        f"restarts {info.get('restarts', 0)}")
    roles: Optional[Dict[str, dict]] = info.get("roles")  # type: ignore
    if roles:
        parts = [
            f"{name}={d.get('replicas', 0)} "
            f"(queue {d.get('queue_depth', 0)}, "
            f"{d.get('migrations', 0)} migrated)"
            for name, d in roles.items()]
        lines.append(
            "  roles: " + " ".join(parts)
            + f"; migrations {info.get('migrations', 0)}")
    lines.append(
        f"  prefix cache: {info.get('prefix_hits', 0)} hits, "
        f"{info.get('prefix_tokens_reused', 0)} tokens reused")
    store: Optional[Dict[str, object]] = \
        info.get("prefix_store")  # type: ignore
    if store:
        hr = store.get("hit_rate")
        lines.append(
            f"  prefix store: {store.get('chains', 0)} chains "
            f"({store.get('spilled_chains', 0)} spilled, "
            f"{store.get('spilled_bytes', 0)} B), "
            f"hits {store.get('hits', 0)} replica / "
            f"{store.get('spill_hits', 0)} spill, "
            f"{store.get('misses', 0)} miss"
            + (f"; hit rate {hr:.2f}" if hr is not None else ""))
    adm: Optional[Dict[str, object]] = \
        info.get("admission")  # type: ignore
    if adm:
        lane_parts = []
        for lane, d in sorted(adm.get("lanes", {}).items()):
            reasons = d.get("shed_reasons") or {}
            why = ", ".join(f"{r}={n}"
                            for r, n in sorted(reasons.items()))
            lane_parts.append(
                f"{lane}={d.get('admitted', 0)} admitted"
                f"/{d.get('shed', 0)} shed"
                + (f" ({why})" if why else ""))
        burn = adm.get("burn_rate", 0.0)
        lines.append(
            "  admission: "
            + ("SHEDDING" if adm.get("shedding") else "open")
            + f" (burn {burn:.2f} on {adm.get('objective', '?')}); "
            + " ".join(lane_parts))
        tenants = adm.get("tenants") or {}
        if tenants:
            t_parts = [
                f"{name}={d.get('used_tokens', 0)}"
                f"/{d.get('budget_tokens', 0)}"
                + (" OVER" if d.get("over") else "")
                for name, d in sorted(tenants.items())]
            lines.append("  tenant budgets: " + " ".join(t_parts))
    sentry: Optional[Dict[str, object]] = \
        info.get("sentry")  # type: ignore
    if sentry:
        lines.append(
            f"  sentry: {sentry.get('sentry_trips', 0)} trip(s), "
            f"canaries {sentry.get('canary_runs', 0)} run / "
            f"{sentry.get('canary_failures', 0)} failed, "
            f"{sentry.get('quarantines', 0)} quarantine(s), "
            f"{sentry.get('tainted_tokens_dropped', 0)} tainted "
            "token(s) dropped")
    perf: Optional[Dict[str, object]] = info.get("perf")  # type: ignore
    if perf:
        mem: Dict[str, float] = perf.get("mem_bytes") or {}  # type: ignore
        if mem:
            lines.append("  memory: " + " ".join(
                f"{pool}={_fmt_bytes(b)}"
                for pool, b in sorted(mem.items())))
        jit: Dict[str, dict] = perf.get("jit") or {}  # type: ignore
        if jit:
            parts = []
            for fam, d in sorted(jit.items()):
                cell = f"{fam}={d.get('compiles', 0)}"
                extra = []
                if d.get("entries"):
                    extra.append(f"{d['entries']} cached")
                if d.get("evictions"):
                    extra.append(f"{d['evictions']} evicted")
                if extra:
                    cell += f" ({', '.join(extra)})"
                parts.append(cell)
            storms = perf.get("retrace_storms", 0)
            lines.append(
                "  jit compiles: " + " ".join(parts)
                + (f"; RETRACE STORMS {storms}" if storms else ""))
    slo: Optional[Dict[str, dict]] = info.get("slo")  # type: ignore
    if slo:
        parts = []
        for name, st in slo.items():
            value = st.get("value")
            shown = "-" if value is None else f"{value:.4g}"
            parts.append(f"{name}={st['state'].upper()}({shown})")
        lines.append("  slo: " + " ".join(parts))
    return "\n".join(lines)
