"""Human-readable fleet status: the operator's one-glance surface.

`render_fleet_status` turns `ServingRouter.fleet_info()` (per-replica
health, queue depths, restart counts, the prefix-cache aggregate, and —
when an `SloMonitor` is attached — per-replica and fleet-level SLO
verdicts) into the fixed-width report `recipes/llama_serve.py` prints
after its drills. Pure formatting: no registry reads, no side effects,
so it can render a `fleet_info()` dict captured anywhere (a log line, a
post-mortem dump, a test)."""
from __future__ import annotations

from typing import Dict, List, Optional

__all__ = ["render_fleet_status"]


def render_fleet_status(info: Dict[str, object]) -> str:
    """Format one `ServingRouter.fleet_info()` snapshot."""
    lines: List[str] = ["fleet status"]
    lines.append(f"  {'replica':<8} {'state':<9} {'outstanding':>11} "
                 f"{'restarts':>8} {'slo':<7} note")
    for r in info.get("replicas", []):
        slo = r.get("slo")
        note = r.get("death_reason") or ""
        if r.get("consecutive_failures"):
            note = (note + " " if note else "") \
                + f"{r['consecutive_failures']} consecutive failures"
        lines.append(
            f"  {r['index']:<8} {r['state']:<9} "
            f"{r['outstanding']:>11} {r['restarts']:>8} "
            f"{(slo.upper() if slo else '-'):<7} {note}".rstrip())
    lines.append(
        f"  requests: {info.get('submitted', 0)} submitted, "
        f"{info.get('pending', 0)} pending; "
        f"failovers {info.get('failovers', 0)}, "
        f"restarts {info.get('restarts', 0)}")
    lines.append(
        f"  prefix cache: {info.get('prefix_hits', 0)} hits, "
        f"{info.get('prefix_tokens_reused', 0)} tokens reused")
    slo: Optional[Dict[str, dict]] = info.get("slo")  # type: ignore
    if slo:
        parts = []
        for name, st in slo.items():
            value = st.get("value")
            shown = "-" if value is None else f"{value:.4g}"
            parts.append(f"{name}={st['state'].upper()}({shown})")
        lines.append("  slo: " + " ".join(parts))
    return "\n".join(lines)
