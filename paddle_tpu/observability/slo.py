"""SLO engine: streaming quantiles + declarative latency objectives.

Production serving is operated on objectives — "p95 TTFT under 500 ms",
"99% of requests succeed" — not on raw counters, and the TPU-serving
comparison literature reports exactly these axes (TTFT/TPOT
percentiles; PAPERS.md "Fine-Tuning and Serving Gemma ... on Google
Cloud TPU"). This module turns the PR-2 telemetry substrate into that
operable layer:

* **Quantiles**, two ways. `quantile_from_buckets` interpolates a
  quantile from the registry's cumulative le-bucket histograms
  (Prometheus `histogram_quantile` semantics: linear within the
  bucket, the highest finite boundary when the quantile lands in
  +Inf) — cheap, streaming, bounded error. `Reservoir` keeps the raw
  samples of a sliding time window (bounded count) and answers EXACT
  quantiles with numpy-percentile linear interpolation — the right
  tool at serving-test sample counts, where bucket interpolation is
  coarse.
* **Objectives.** `SloObjective` declares one target — a latency
  quantile bound (`kind="latency"`), a max error rate
  (`kind="error_rate"`), or a min availability
  (`kind="availability"`) — over a rolling window. `SloMonitor`
  ingests samples (`observe` for latencies, `observe_outcome` for
  success/failure, optionally per replica), and `evaluate()` grades
  each objective **pass / warn / breach** with a BURN RATE: the
  fraction of the error budget being consumed (for "p95 <= T" the
  budget is the 5% of requests allowed past T; burn 1.0 = consuming
  it exactly, >1.0 = breach, >= `warn_burn` = warn). Results export
  as `pdt_slo_value` / `pdt_slo_burn_rate` / `pdt_slo_state{objective=}`
  gauges so the SLO verdicts themselves land in the scrape.
* **Offline evaluation.** `evaluate_snapshot` grades the same
  objectives against a saved `telemetry.snapshot()` (latencies from
  the le-bucket histograms, error rate / availability from the
  terminal-status counters) — the `python -m paddle_tpu.observability
  slo` CLI path, no live process required.

The serving router takes an optional read-only `slo_monitor=` hook and
feeds it terminal outcomes + TTFT per request, so `fleet_info()` can
report per-replica SLO state alongside health (docs/observability.md).
"""
from __future__ import annotations

import json
import math
import time
from collections import deque
from dataclasses import dataclass, fields
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

from .registry import gauge

__all__ = ["PASS", "WARN", "BREACH", "STATE_CODE",
           "quantile_from_buckets", "fraction_over_threshold",
           "Reservoir", "SloObjective", "SloStatus", "SloMonitor",
           "default_serving_objectives", "objectives_from_spec",
           "evaluate_snapshot", "format_slo_report"]

PASS, WARN, BREACH = "pass", "warn", "breach"
# the pdt_slo_state gauge encoding (docs/observability.md)
STATE_CODE = {PASS: 0, WARN: 1, BREACH: 2}

_M_SLO_VALUE = gauge(
    "pdt_slo_value",
    "Measured value per objective (latency quantile in seconds, or "
    "the error/availability ratio).", ("objective",))
_M_SLO_BURN = gauge(
    "pdt_slo_burn_rate",
    "Error-budget burn rate per objective (1.0 = consuming the budget "
    "exactly; > 1.0 = breach; infinite burns on zero-budget "
    "objectives export capped at 1e9).", ("objective",))
_M_SLO_STATE = gauge(
    "pdt_slo_state",
    "Objective verdict (0=pass 1=warn 2=breach).", ("objective",))


# -- quantile math -----------------------------------------------------
def _bucket_items(buckets: Dict[str, float]) -> List[Tuple[float, float]]:
    items = []
    for le, c in buckets.items():
        b = math.inf if le == "+Inf" else float(le)
        items.append((b, float(c)))
    items.sort()
    return items


def quantile_from_buckets(buckets: Dict[str, float],
                          q: float) -> Optional[float]:
    """Interpolated quantile from a snapshot histogram's CUMULATIVE
    le-bucket map (`{"0.1": 3, "1": 7, "+Inf": 9}`) — Prometheus
    `histogram_quantile` semantics: linear interpolation inside the
    bucket the rank lands in (lower bound 0 for the first), and the
    highest finite boundary when it lands in +Inf. None when empty."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    items = _bucket_items(buckets)
    if not items or items[-1][1] <= 0:
        return None
    rank = q * items[-1][1]
    prev_b, prev_c = 0.0, 0.0
    for b, c in items:
        if c >= rank and c > prev_c:
            if math.isinf(b):
                finite = [x for x, _ in items if not math.isinf(x)]
                return finite[-1] if finite else None
            frac = (rank - prev_c) / (c - prev_c)
            return prev_b + (b - prev_b) * min(max(frac, 0.0), 1.0)
        prev_b, prev_c = b, c
    return items[-1][0] if not math.isinf(items[-1][0]) else None


def fraction_over_threshold(buckets: Dict[str, float],
                            threshold: float) -> Optional[float]:
    """Estimated fraction of observations STRICTLY above `threshold`,
    interpolating linearly within the bucket containing it (the
    burn-rate numerator on the histogram path). When the threshold
    lies beyond every finite boundary, the +Inf bucket's mass cannot
    be placed relative to it and counts as OVER — an unresolvable
    threshold must grade conservatively, never as a confident pass.
    None when empty."""
    items = _bucket_items(buckets)
    if not items or items[-1][1] <= 0:
        return None
    total = items[-1][1]
    prev_b, prev_c = 0.0, 0.0
    for b, c in items:
        if threshold <= b:
            if math.isinf(b):
                at = prev_c        # +Inf mass: only ">last finite
                #                    boundary" is known — count it over
            else:
                width = b - prev_b
                frac = 1.0 if width <= 0 \
                    else (threshold - prev_b) / width
                at = prev_c + (c - prev_c) * min(max(frac, 0.0), 1.0)
            return max(0.0, (total - at) / total)
        prev_b, prev_c = b, c
    return 0.0


def exact_quantile(values: Sequence[float], q: float) -> Optional[float]:
    """numpy-percentile (linear interpolation) quantile of raw values —
    the Reservoir path's math, exposed for reuse and golden tests."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    vals = sorted(float(v) for v in values)
    if not vals:
        return None
    pos = q * (len(vals) - 1)
    lo = int(math.floor(pos))
    hi = int(math.ceil(pos))
    if lo == hi:
        return vals[lo]
    return vals[lo] + (vals[hi] - vals[lo]) * (pos - lo)


class Reservoir:
    """Sliding-window sample store for EXACT small-N quantiles: keeps
    the last `max_samples` observations no older than `window_s` on the
    injectable clock, answers `quantile`/`fraction_over` with the same
    linear interpolation as `numpy.percentile`. O(1) ingest, bounded
    memory; expiry happens lazily on both ingest and read. Samples may
    carry a `tag` (the SloMonitor uses the serving replica) and
    `values(tag=...)` reads one tag's slice — the window semantics
    live HERE, once, for every consumer."""

    def __init__(self, window_s: float = 60.0, max_samples: int = 2048,
                 clock: Optional[Callable[[], float]] = None):
        if window_s <= 0 or max_samples < 1:
            raise ValueError("window_s must be > 0 and max_samples >= 1")
        self.window_s = float(window_s)
        self.max_samples = int(max_samples)
        self._clock = clock if clock is not None else time.monotonic
        self._samples: Deque[Tuple[float, float, Optional[str]]] = \
            deque()

    def observe(self, value: float, now: Optional[float] = None,
                tag: Optional[str] = None):
        now = self._clock() if now is None else now
        self._samples.append((now, float(value), tag))
        while len(self._samples) > self.max_samples:
            self._samples.popleft()
        self._expire(now)

    def _expire(self, now: float):
        cutoff = now - self.window_s
        while self._samples and self._samples[0][0] <= cutoff:
            self._samples.popleft()

    def values(self, now: Optional[float] = None,
               tag: Optional[str] = None) -> List[float]:
        self._expire(self._clock() if now is None else now)
        return [v for _, v, t in self._samples
                if tag is None or t == tag]

    def __len__(self) -> int:
        return len(self.values())

    def quantile(self, q: float,
                 now: Optional[float] = None) -> Optional[float]:
        return exact_quantile(self.values(now), q)

    def fraction_over(self, threshold: float,
                      now: Optional[float] = None) -> Optional[float]:
        vals = self.values(now)
        if not vals:
            return None
        return sum(1 for v in vals if v > threshold) / len(vals)


# -- objectives --------------------------------------------------------
@dataclass(frozen=True)
class SloObjective:
    """One declarative objective (the JSON spec format mirrors these
    fields 1:1 — docs/observability.md "SLO spec").

    * `kind="latency"`: "the `quantile` of `signal` latencies is <=
      `threshold` seconds". Budget = the (1 - quantile) fraction of
      requests allowed past the threshold.
    * `kind="error_rate"`: "the failing fraction of outcomes is <=
      `threshold`". Budget = `threshold` itself.
    * `kind="availability"`: "the succeeding fraction of outcomes is
      >= `threshold`". Budget = 1 - `threshold`.

    `signal` is the feed key (`SloMonitor.observe(signal, ...)`), so
    several objectives can grade one stream (p50 and p95 of the same
    TTFT feed). `metric` names the registry series used when no live
    samples exist: a histogram for latency objectives, the
    terminal-status counter for ratio objectives (offline
    `evaluate_snapshot` uses it exclusively)."""

    name: str
    signal: str
    kind: str                      # latency | error_rate | availability
    threshold: float
    quantile: float = 0.95         # latency only
    window_s: float = 60.0
    metric: Optional[str] = None

    def __post_init__(self):
        if self.kind not in ("latency", "error_rate", "availability"):
            raise ValueError(f"objective {self.name!r}: unknown kind "
                             f"{self.kind!r}")
        if self.kind == "latency" and not 0.0 < self.quantile < 1.0:
            raise ValueError(f"objective {self.name!r}: quantile must "
                             f"be in (0, 1), got {self.quantile}")
        if self.kind != "latency" and not 0.0 <= self.threshold <= 1.0:
            raise ValueError(f"objective {self.name!r}: ratio threshold "
                             f"must be in [0, 1], got {self.threshold}")


@dataclass
class SloStatus:
    """One objective's verdict from an evaluation pass."""

    objective: str
    kind: str
    state: str                     # pass | warn | breach
    threshold: float
    value: Optional[float] = None  # quantile seconds, or the ratio
    burn_rate: float = 0.0
    samples: int = 0
    source: str = "none"           # reservoir | histogram | counter | none

    @property
    def ok(self) -> bool:
        return self.state != BREACH


def _grade(bad_fraction: Optional[float], budget: float,
           warn_burn: float) -> Tuple[str, float]:
    """(state, burn rate) from the observed bad fraction vs the error
    budget. No data (None) grades pass at burn 0 — absence of traffic
    is not a breach."""
    if bad_fraction is None:
        return PASS, 0.0
    if budget <= 0:
        burn = math.inf if bad_fraction > 0 else 0.0
    else:
        burn = bad_fraction / budget
    if burn > 1.0:
        return BREACH, burn
    if burn >= warn_burn:
        return WARN, burn
    return PASS, burn


def default_serving_objectives(ttft_p95: float = 0.5,
                               tpot_p95: float = 0.1,
                               max_error_rate: float = 0.01,
                               min_availability: float = 0.99,
                               window_s: float = 60.0) \
        -> List[SloObjective]:
    """The stock serving objective set: TTFT p95, TPOT p95, error
    rate, availability — fed by the router hook (signals `ttft` /
    `tpot` / `outcome`) and evaluable offline from the
    `pdt_serving_*` metrics."""
    return [
        SloObjective("ttft_p95", "ttft", "latency", ttft_p95,
                     quantile=0.95, window_s=window_s,
                     metric="pdt_serving_ttft_seconds"),
        SloObjective("tpot_p95", "tpot", "latency", tpot_p95,
                     quantile=0.95, window_s=window_s,
                     metric="pdt_serving_tpot_seconds"),
        SloObjective("error_rate", "outcome", "error_rate",
                     max_error_rate, window_s=window_s,
                     metric="pdt_serving_requests_terminal_total"),
        SloObjective("availability", "outcome", "availability",
                     min_availability, window_s=window_s,
                     metric="pdt_serving_requests_terminal_total"),
    ]


def objectives_from_spec(spec) -> List[SloObjective]:
    """Build objectives from the JSON spec format: a list of dicts
    whose keys mirror `SloObjective` fields, or a path to a JSON file
    holding one. Unknown keys raise (a typo'd spec must not silently
    grade pass)."""
    if isinstance(spec, str):
        with open(spec) as f:
            spec = json.load(f)
    allowed = {f.name for f in fields(SloObjective)}
    out = []
    for d in spec:
        unknown = set(d) - allowed
        if unknown:
            raise ValueError(f"SLO spec entry {d.get('name', d)!r}: "
                             f"unknown keys {sorted(unknown)}")
        out.append(SloObjective(**d))
    return out


class SloMonitor:
    """Live objective evaluation over rolling windows (module
    docstring). Deterministic: pass the fleet's fake clock in tests.
    `replica=` tags samples so `replica_state()` can grade one
    replica's slice of the traffic (the router's `fleet_info` hook)."""

    def __init__(self, objectives: Optional[Sequence[SloObjective]] = None,
                 *, clock: Optional[Callable[[], float]] = None,
                 warn_burn: float = 0.5, max_samples: int = 4096):
        self._clock = clock if clock is not None else time.monotonic
        self.warn_burn = float(warn_burn)
        self.max_samples = int(max_samples)
        self.objectives: Dict[str, SloObjective] = {}
        # one Reservoir per objective (outcomes stored as 1.0/0.0) —
        # the window/cap semantics live in the golden-tested class
        self._res: Dict[str, Reservoir] = {}
        for obj in (objectives if objectives is not None
                    else default_serving_objectives()):
            self.add_objective(obj)

    def add_objective(self, obj: SloObjective):
        if obj.name in self.objectives:
            raise ValueError(f"objective {obj.name!r} already added")
        self.objectives[obj.name] = obj
        self._res[obj.name] = Reservoir(window_s=obj.window_s,
                                        max_samples=self.max_samples,
                                        clock=self._clock)

    # -- ingest --------------------------------------------------------
    def observe(self, signal: str, seconds: float,
                replica: Optional[str] = None):
        """Record one latency sample for every `kind="latency"`
        objective fed by `signal`."""
        for obj in self.objectives.values():
            if obj.kind == "latency" and obj.signal == signal:
                self._res[obj.name].observe(float(seconds),
                                            tag=replica)

    def observe_outcome(self, signal: str, ok: bool,
                        replica: Optional[str] = None):
        """Record one success/failure outcome for every ratio
        objective (`error_rate` / `availability`) fed by `signal`."""
        for obj in self.objectives.values():
            if obj.kind != "latency" and obj.signal == signal:
                self._res[obj.name].observe(1.0 if ok else 0.0,
                                            tag=replica)

    def _window(self, obj: SloObjective, now: float,
                replica: Optional[str] = None) -> List[float]:
        return self._res[obj.name].values(now, tag=replica)

    # -- evaluation ----------------------------------------------------
    def _grade_latency(self, obj: SloObjective, vals: List[float]) \
            -> SloStatus:
        st = SloStatus(obj.name, obj.kind, PASS, obj.threshold,
                       samples=len(vals))
        if vals:
            st.value = exact_quantile(vals, obj.quantile)
            bad = sum(1 for v in vals if v > obj.threshold) / len(vals)
            st.source = "reservoir"
        else:
            series = _histogram_series(obj.metric)
            if series is None:
                return st
            st.value = quantile_from_buckets(series["buckets"],
                                             obj.quantile)
            bad = fraction_over_threshold(series["buckets"],
                                          obj.threshold)
            st.samples = int(series.get("count", 0))
            st.source = "histogram"
        st.state, st.burn_rate = _grade(bad, 1.0 - obj.quantile,
                                        self.warn_burn)
        return st

    def _grade_ratio(self, obj: SloObjective,
                     outcomes: List[float]) -> SloStatus:
        st = SloStatus(obj.name, obj.kind, PASS, obj.threshold,
                       samples=len(outcomes))
        if not outcomes:
            return st
        st.source = "reservoir"
        bad = sum(1 for v in outcomes if v < 0.5) / len(outcomes)
        if obj.kind == "error_rate":
            st.value = bad
            budget = obj.threshold
        else:                                  # availability
            st.value = 1.0 - bad
            budget = 1.0 - obj.threshold
        st.state, st.burn_rate = _grade(bad, budget, self.warn_burn)
        return st

    def _evaluate_one(self, obj: SloObjective, now: float,
                      replica: Optional[str] = None) -> SloStatus:
        window = self._window(obj, now, replica)
        if obj.kind == "latency":
            if replica is not None and not window:
                # per-replica grading never falls back to the GLOBAL
                # histogram — that would grade every replica identically
                return SloStatus(obj.name, obj.kind, PASS,
                                 obj.threshold)
            return self._grade_latency(obj, window)
        return self._grade_ratio(obj, window)

    def evaluate(self, export: bool = True) -> Dict[str, SloStatus]:
        """Grade every objective now; optionally export the
        `pdt_slo_*` gauges. Returns {objective name: SloStatus}."""
        now = self._clock()
        out = {}
        for name, obj in self.objectives.items():
            st = self._evaluate_one(obj, now)
            out[name] = st
            if export:
                if st.value is not None:
                    _M_SLO_VALUE.set(st.value, objective=name)
                # an infinite burn (zero-budget objective violated)
                # exports as the 1e9 cap: still wildly > any alert
                # threshold, unlike a sentinel a `burn > 1` rule would
                # miss, and finite so the text exposition stays valid
                _M_SLO_BURN.set(min(st.burn_rate, 1e9), objective=name)
                _M_SLO_STATE.set(STATE_CODE[st.state], objective=name)
        return out

    def replica_state(self, replica: str) -> Optional[str]:
        """Worst objective state over THIS replica's samples (None when
        the replica contributed no samples at all) — read by
        `ServingRouter.fleet_info` to report SLO next to health."""
        now = self._clock()
        worst = None
        for obj in self.objectives.values():
            if not self._window(obj, now, replica):
                continue
            st = self._evaluate_one(obj, now, replica)
            if worst is None or STATE_CODE[st.state] > STATE_CODE[worst]:
                worst = st.state
        return worst

    def report(self) -> str:
        """Human-readable objective report (the operator surface)."""
        return format_slo_report(self.evaluate(export=False),
                                 warn_burn=self.warn_burn)


def _histogram_series(metric: Optional[str]) -> Optional[dict]:
    """The unlabelled series of `metric` from the LIVE registry
    (cumulative since the last reset), or None."""
    if metric is None:
        return None
    from .registry import snapshot
    series = snapshot()["histograms"].get(metric, {}).get("")
    return series if series and series.get("count") else None


# -- offline path ------------------------------------------------------
_BAD_STATUSES = ("failed", "timeout", "preempted")


def evaluate_snapshot(snap: dict,
                      objectives: Optional[Sequence[SloObjective]] = None,
                      warn_burn: float = 0.5) -> Dict[str, SloStatus]:
    """Grade objectives against a saved `telemetry.snapshot()` (the
    CLI path): latency objectives from their `metric` histogram's
    le buckets, ratio objectives from the per-status terminal counter
    named by `metric` (bad = failed|timeout|preempted). Objectives
    whose metric is absent grade pass with source "none"."""
    objectives = (default_serving_objectives()
                  if objectives is None else objectives)
    out: Dict[str, SloStatus] = {}
    for obj in objectives:
        st = SloStatus(obj.name, obj.kind, PASS, obj.threshold)
        if obj.kind == "latency":
            series = (snap.get("histograms", {})
                      .get(obj.metric or "", {}).get(""))
            if series and series.get("count"):
                st.value = quantile_from_buckets(series["buckets"],
                                                 obj.quantile)
                bad = fraction_over_threshold(series["buckets"],
                                              obj.threshold)
                st.samples = int(series["count"])
                st.source = "histogram"
                st.state, st.burn_rate = _grade(
                    bad, 1.0 - obj.quantile, warn_burn)
        else:
            series = (snap.get("counters", {})
                      .get(obj.metric or "", {}))
            total = sum(series.values())
            if total > 0:
                bad = sum(v for k, v in series.items()
                          if any(f'status="{s}"' in k
                                 for s in _BAD_STATUSES)) / total
                st.samples = int(total)
                st.source = "counter"
                if obj.kind == "error_rate":
                    st.value, budget = bad, obj.threshold
                else:
                    st.value, budget = 1.0 - bad, 1.0 - obj.threshold
                st.state, st.burn_rate = _grade(bad, budget, warn_burn)
        out[obj.name] = st
    return out


def format_slo_report(statuses: Dict[str, SloStatus],
                      warn_burn: float = 0.5) -> str:
    """Fixed-width objective table (recipes + the `slo` CLI command)."""
    lines = [f"SLO report ({len(statuses)} objectives, "
             f"warn at burn >= {warn_burn:g})",
             f"  {'objective':<16} {'state':<7} {'value':>12} "
             f"{'threshold':>10} {'burn':>8}  source"]
    for name, st in statuses.items():
        value = "-" if st.value is None else f"{st.value:.6g}"
        burn = "inf" if math.isinf(st.burn_rate) \
            else f"{st.burn_rate:.2f}"
        lines.append(
            f"  {name:<16} {st.state.upper():<7} {value:>12} "
            f"{st.threshold:>10.6g} {burn:>8}  "
            f"{st.source}({st.samples})")
    return "\n".join(lines)
