"""Exporters: Prometheus text exposition + JSON snapshot (+ parse-back).

`to_prometheus()` renders the global registry in the Prometheus text
exposition format (version 0.0.4): `# HELP` / `# TYPE` headers, one
sample per series, histograms as cumulative `_bucket{le=...}` +
`_sum` + `_count`. `parse_prometheus()` reads that text back into the
exact `snapshot()` structure, so round-trip equality
(`parse_prometheus(to_prometheus()) == snapshot()` minus the `enabled`
flag) is an invariant the test suite asserts — the dump a scraper sees
IS the state the process had.

`to_json()` / `write_json()` give the same data as a machine-readable
snapshot for JSONL trajectories (bench detail, post-mortem dumps).
"""
from __future__ import annotations

import json
from typing import Dict, Optional

from .registry import (REGISTRY, Counter, Gauge, Histogram, Registry,
                       _escape_label_value, _fmt_float)

__all__ = ["to_prometheus", "render_prometheus", "to_json",
           "write_json", "parse_prometheus"]


def _sample(name: str, labels: str, v) -> str:
    body = f"{{{labels}}}" if labels else ""
    return f"{name}{body} {_fmt_float(float(v))}"


def _merge_label(labels: str, extra: str) -> str:
    return f"{labels},{extra}" if labels else extra


def to_prometheus(registry: Optional[Registry] = None) -> str:
    """Text exposition of every live series, deterministically ordered
    (by instrument name, then label string)."""
    reg = registry if registry is not None else REGISTRY
    insts = reg.instruments()
    return _render(reg.snapshot(),
                   {n: i.help for n, i in insts.items() if i.help})


def render_prometheus(snapshot: Dict[str, object]) -> str:
    """Text exposition straight from a bare `snapshot()` STRUCTURE —
    no live registry required, so offline tooling (the
    `python -m paddle_tpu.observability snapshot` CLI) can convert a
    saved JSON snapshot into scrape text. `# HELP` lines are omitted
    (snapshots do not carry help strings);
    `parse_prometheus(render_prometheus(snap))` still round-trips to
    the same values."""
    return _render(snapshot, {})


def _render(snap: Dict[str, object], helps: Dict[str, str]) -> str:
    lines = []
    for kind, section in (("counter", "counters"), ("gauge", "gauges"),
                          ("histogram", "histograms")):
        for name, series in sorted(snap.get(section, {}).items()):
            if not series:
                continue
            if name in helps:
                lines.append(f"# HELP {name} {helps[name]}")
            lines.append(f"# TYPE {name} {kind}")
            for labels, val in sorted(series.items()):
                if kind == "histogram":
                    exemplars = val.get("exemplars", {})
                    for le, c in val["buckets"].items():
                        line = _sample(
                            name + "_bucket",
                            _merge_label(labels, f'le="{le}"'), c)
                        ex = exemplars.get(le)
                        if ex is not None:
                            # OpenMetrics exemplar syntax:
                            #   ... 3 # {trace_id="abc"} 0.043
                            tid = _escape_label_value(
                                str(ex["trace_id"]))
                            line += (f' # {{trace_id="{tid}"}} '
                                     f'{_fmt_float(float(ex["value"]))}')
                        lines.append(line)
                    lines.append(_sample(name + "_sum", labels,
                                         val["sum"]))
                    lines.append(_sample(name + "_count", labels,
                                         val["count"]))
                else:
                    lines.append(_sample(name, labels, val))
    return "\n".join(lines) + ("\n" if lines else "")


def to_json(registry: Optional[Registry] = None) -> Dict[str, object]:
    reg = registry if registry is not None else REGISTRY
    return reg.snapshot()


def write_json(path: str, registry: Optional[Registry] = None):
    with open(path, "w") as f:
        json.dump(to_json(registry), f, indent=2, sort_keys=True)
        f.write("\n")


def _parse_label_body(body: str, line: str) -> Dict[str, str]:
    """Quote-aware `a="x",b="y"` parser (values may contain commas);
    values are kept in their ESCAPED exposition form, matching the
    canonical label-string snapshot keys."""
    labels: Dict[str, str] = {}
    i = 0
    while i < len(body):
        eq = body.index("=", i)
        key = body[i:eq].strip(" ,")
        assert body[eq + 1] == '"', f"unquoted label value in {line!r}"
        j = eq + 2
        while body[j] != '"':
            j += 2 if body[j] == "\\" else 1
        labels[key] = body[eq + 2:j]
        i = j + 1
    return labels


def _unescape_label_value(v: str) -> str:
    """Inverse of `_escape_label_value` (one left-to-right scan, so
    `\\\\n` decodes as backslash+n, not backslash+newline)."""
    out, i = [], 0
    while i < len(v):
        if v[i] == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            out.append({"n": "\n", '"': '"', "\\": "\\"}.get(
                nxt, "\\" + nxt))
            i += 2
        else:
            out.append(v[i])
            i += 1
    return "".join(out)


def _split_exemplar(line: str):
    """Strip an OpenMetrics exemplar suffix:
    `name_bucket{le="1"} 3 # {trace_id="abc"} 0.043` ->
    (`name_bucket{le="1"} 3`, {"trace_id": "abc", "value": 0.043}).
    Returns (line, None) when no exemplar is present."""
    cut = line.find(" # {")
    if cut < 0:
        return line, None
    tail = line[cut + 3:]                    # '{trace_id="..."} 0.043'
    end = tail.rfind("}")
    labels = _parse_label_body(tail[1:end], line)
    return line[:cut], {
        "trace_id": _unescape_label_value(labels.get("trace_id", "")),
        "value": float(tail[end + 1:].strip())}


def _split_sample(line: str):
    """`name{a="x",le="1"} 3` -> (name, {"a": "x", "le": "1"}, 3.0).
    Label values are parsed quote-aware (values may contain commas)."""
    brace = line.find("{")
    if brace < 0:
        name, _, num = line.rpartition(" ")
        return name.strip(), {}, float(num)
    name = line[:brace]
    endbrace = line.rfind("}")
    body, num = line[brace + 1:endbrace], line[endbrace + 1:]
    return name, _parse_label_body(body, line), float(num.strip())


def parse_prometheus(text: str) -> Dict[str, object]:
    """Parse a text exposition back into the `snapshot()` structure
    (sans the `enabled` flag). Built for round-trip verification of our
    own exporter — it understands the full sample syntax but only the
    three instrument kinds we emit."""
    out = {"counters": {}, "gauges": {}, "histograms": {}}
    types: Dict[str, str] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] == "TYPE":
                types[parts[2]] = parts[3].strip() if len(parts) > 3 \
                    else "untyped"
            continue
        line, exemplar = _split_exemplar(line)
        name, labels, val = _split_sample(line)
        base, suffix = name, None
        for sfx in ("_bucket", "_sum", "_count"):
            if name.endswith(sfx) and types.get(name[:-len(sfx)]) \
                    == "histogram":
                base, suffix = name[:-len(sfx)], sfx
                break
        kind = types.get(base, "untyped")
        if kind == "histogram":
            le = labels.pop("le", None)
            lstr = ",".join(f'{k}="{v}"' for k, v in labels.items())
            series = out["histograms"].setdefault(base, {}).setdefault(
                lstr, {"count": 0, "sum": 0.0, "buckets": {}})
            if suffix == "_bucket":
                series["buckets"][le] = int(val)
                if exemplar is not None:
                    series.setdefault("exemplars", {})[le] = exemplar
            elif suffix == "_sum":
                series["sum"] = val
            elif suffix == "_count":
                series["count"] = int(val)
        elif kind in ("counter", "gauge"):
            lstr = ",".join(f'{k}="{v}"' for k, v in labels.items())
            out["counters" if kind == "counter" else "gauges"
                ].setdefault(base, {})[lstr] = val
    return out
