"""Span tracing: nestable host-side spans -> structured JSONL events.

Each completed span (and each point `event()`) becomes one dict —
`{"name", "attrs", "ts", "dur_s", "seq", "depth", "parent"}` — appended
to a bounded in-memory ring buffer (oldest dropped first, so a serving
process can trace forever in O(1) memory) and, when a file sink is
configured (`set_trace_file()` or `PDT_TELEMETRY_TRACE_FILE=`), written
as one JSON line for offline tooling (`jq`, pandas, Perfetto
converters).

Spans NEST via a per-thread stack: `depth` and `parent` (the enclosing
span's seq no) reconstruct the tree, and `seq` is a process-global
monotone sequence so interleaved threads stay ordered. Timing is the
monotonic clock (`time.perf_counter`); `ts` is wall time for log
correlation only.

Interop with the profiler shim: when telemetry is enabled, each span
also enters a `paddle_tpu.profiler.RecordEvent`, so the same host span
lands in the XLA timeline (TraceAnnotation) and in
`Profiler.summary()`'s host-stats table. The import is lazy and
fault-tolerant — the ring buffer works in processes that never import
jax.

Like the metrics registry, spans are a guaranteed no-op while telemetry
is disabled: `span()` returns a singleton null context manager and
`event()` returns immediately.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from .registry import enabled

__all__ = ["span", "event", "events", "clear", "set_trace_file",
           "trace_file"]

_RING_CAP = int(os.environ.get("PDT_TELEMETRY_TRACE_CAP", "4096"))
_LOCK = threading.Lock()
_RING: "deque[dict]" = deque(maxlen=_RING_CAP)
_SEQ = itertools.count()
_TLS = threading.local()

_SINK_PATH: Optional[str] = None
_SINK_FILE = None
# True once the sink target is settled — either set_trace_file() was
# called (its choice is final, including an explicit None = off) or the
# env var has been consulted; _emit must not re-read the env after that
_SINK_RESOLVED = False

# paddle_tpu.profiler.RecordEvent, resolved lazily; False = unavailable
_RECORD_EVENT = None


def _record_event_cls():
    global _RECORD_EVENT
    if _RECORD_EVENT is None:
        try:
            from ..profiler import RecordEvent
            _RECORD_EVENT = RecordEvent
        except Exception:
            _RECORD_EVENT = False
    return _RECORD_EVENT


def set_trace_file(path: Optional[str]):
    """Route every event to `path` as JSON lines (append). None closes
    the sink. Overrides `PDT_TELEMETRY_TRACE_FILE` either way — after
    set_trace_file(None) the env var is NOT re-consulted."""
    global _SINK_PATH, _SINK_FILE, _SINK_RESOLVED
    with _LOCK:
        if _SINK_FILE is not None:
            _SINK_FILE.close()
            _SINK_FILE = None
        _SINK_PATH = path
        _SINK_RESOLVED = True


def trace_file() -> Optional[str]:
    return _SINK_PATH


def _emit(ev: dict):
    global _SINK_PATH, _SINK_FILE, _SINK_RESOLVED
    with _LOCK:
        _RING.append(ev)
        if not _SINK_RESOLVED:
            _SINK_PATH = os.environ.get("PDT_TELEMETRY_TRACE_FILE") \
                or None
            _SINK_RESOLVED = True      # consult the env only once
        if _SINK_PATH is not None:
            if _SINK_FILE is None:
                _SINK_FILE = open(_SINK_PATH, "a", buffering=1)
            _SINK_FILE.write(json.dumps(ev) + "\n")


def events() -> List[dict]:
    """Snapshot of the ring buffer, oldest first."""
    with _LOCK:
        return list(_RING)


def clear():
    with _LOCK:
        _RING.clear()


class _NullSpan:
    """Disabled-mode span: no state, no clock reads, reusable."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("name", "attrs", "_t0", "_ts", "_seq", "_depth",
                 "_parent", "_rec")

    def __init__(self, name: str, attrs: Dict[str, object]):
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        stack = getattr(_TLS, "stack", None)
        if stack is None:
            stack = _TLS.stack = []
        self._seq = next(_SEQ)
        self._depth = len(stack)
        self._parent = stack[-1] if stack else None
        stack.append(self._seq)
        rec_cls = _record_event_cls()
        self._rec = None
        if rec_cls:
            try:
                self._rec = rec_cls(self.name)
                self._rec.begin()
            except Exception:
                self._rec = None       # profiler backend unavailable
        self._ts = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        if self._rec is not None:
            try:
                self._rec.end()
            except Exception:
                pass
        stack = _TLS.stack
        if stack and stack[-1] == self._seq:
            stack.pop()
        ev = {"name": self.name, "attrs": self.attrs, "ts": self._ts,
              "dur_s": dur, "seq": self._seq, "depth": self._depth,
              "parent": self._parent}
        if exc_type is not None:
            ev["attrs"] = dict(self.attrs,
                               error=f"{exc_type.__name__}: {exc}")
        _emit(ev)
        return False


def span(name: str, **attrs):
    """`with span("serving.decode_step", slots=3): ...` — records one
    JSONL event on exit (duration, nesting, attrs; an escaping
    exception lands in `attrs["error"]`). No-op while disabled."""
    if not enabled():
        return _NULL_SPAN
    return _Span(name, attrs)


def event(name: str, **attrs):
    """Point event (zero-duration span): fault fires, restarts,
    membership changes. No-op while disabled."""
    if not enabled():
        return
    stack = getattr(_TLS, "stack", None) or []
    _emit({"name": name, "attrs": attrs, "ts": time.time(),
           "dur_s": 0.0, "seq": next(_SEQ), "depth": len(stack),
           "parent": stack[-1] if stack else None})
