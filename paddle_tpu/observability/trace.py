"""Span tracing: nestable host-side spans -> structured JSONL events,
with REQUEST-SCOPED distributed traces across the serving fleet.

Each completed span (and each point `event()`) becomes one dict —
`{"name", "attrs", "ts", "ts_mono", "dur_s", "seq", "depth", "parent",
"trace"}` — appended to a bounded in-memory ring buffer (oldest dropped
first, so a serving process can trace forever in O(1) memory) and, when
a file sink is configured (`set_trace_file()` or
`PDT_TELEMETRY_TRACE_FILE=`), written as one JSON line for offline
tooling (`jq`, pandas, the Chrome/Perfetto exporter below).

Spans NEST via a per-thread stack: `parent` (the enclosing span's seq
no) and `depth` reconstruct the local tree, and `seq` is a
process-global monotone sequence so interleaved threads stay ordered.

ONE CLOCK: every event is stamped from a single monotonic clock
(`time.perf_counter`) captured at span START (`ts_mono`); `dur_s` is
measured on the same clock, and the wall-time `ts` is DERIVED from one
process-wide (wall, mono) base pair — so timestamps from nested spans,
point events, and different requests are mutually comparable and
durations reconstruct exactly from the JSONL alone.

DISTRIBUTED TRACES (the fleet-router contract): a trace is opened per
request with `start_trace(request_id)` — the request_id is the PR-4
stable id that follows a request across replicas — which registers a
(trace id, root span) CARRIER under that key. From then on, ANY span or
event whose attrs carry that `request_id` joins the trace
automatically: it inherits the trace id and, when it has no enclosing
span, parents under the trace root. `attach(request_id)` joins
explicitly for blocks that cannot carry the attr. The router opens the
trace at submit, the replica/engine spans carry `request_id`, and
failover re-dispatch keeps the same id — so one request's dispatch,
queue wait, prefill, decode steps, preemptions, and failover form a
single causal tree (`request_tree()` rebuilds it; `export_chrome_trace`
renders it for chrome://tracing / Perfetto with pid=replica,
tid=request). `end_trace(request_id)` drops the carrier once the
request is terminal (the carrier table is LRU-bounded either way).

Interop with the profiler shim: when telemetry is enabled, each span
also enters a `paddle_tpu.profiler.RecordEvent`, so the same host span
lands in the XLA timeline (TraceAnnotation) and in
`Profiler.summary()`'s host-stats table. The import is lazy and
fault-tolerant — the ring buffer works in processes that never import
jax.

Like the metrics registry, spans are a guaranteed no-op while telemetry
is disabled: `span()` returns a singleton null context manager,
`event()` / `start_trace()` return immediately.
"""
from __future__ import annotations

import contextlib
import itertools
import json
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional

from .registry import enabled

__all__ = ["span", "event", "events", "clear", "set_trace_file",
           "trace_file", "start_trace", "end_trace", "trace_of",
           "attach", "request_tree", "format_tree",
           "export_chrome_trace", "load_trace_jsonl"]

_RING_CAP = int(os.environ.get("PDT_TELEMETRY_TRACE_CAP", "4096"))
_LOCK = threading.Lock()
_RING: "deque[dict]" = deque(maxlen=_RING_CAP)
_SEQ = itertools.count()
_TLS = threading.local()

# -- the one clock ----------------------------------------------------
# Every stamp is perf_counter; wall time is DERIVED from this base pair
# so `ts` values across the whole ring share one timeline (the
# duration-reconstruction contract in the module docstring).
_CLOCK = time.perf_counter
_T0_MONO = _CLOCK()
_T0_WALL = time.time()


def _wall(mono: float) -> float:
    return _T0_WALL + (mono - _T0_MONO)


# -- request-scoped trace carriers ------------------------------------
_TRACE_IDS = itertools.count(1)
_CARRIER_CAP = int(os.environ.get("PDT_TELEMETRY_TRACE_CARRIERS",
                                  "4096"))
_CARRIER_LOCK = threading.Lock()
# carrier key (request_id) -> (trace id, root span seq); LRU-bounded so
# a caller that never calls end_trace cannot grow this without bound
_CARRIERS: "OrderedDict[str, tuple]" = OrderedDict()

_SINK_PATH: Optional[str] = None
_SINK_FILE = None
# True once the sink target is settled — either set_trace_file() was
# called (its choice is final, including an explicit None = off) or the
# env var has been consulted; _emit must not re-read the env after that
_SINK_RESOLVED = False

# paddle_tpu.profiler.RecordEvent, resolved lazily; False = unavailable
_RECORD_EVENT = None


def _record_event_cls():
    global _RECORD_EVENT
    if _RECORD_EVENT is None:
        try:
            from ..profiler import RecordEvent
            _RECORD_EVENT = RecordEvent
        except Exception:
            _RECORD_EVENT = False
    return _RECORD_EVENT


def set_trace_file(path: Optional[str]):
    """Route every event to `path` as JSON lines (append). None closes
    the sink. Overrides `PDT_TELEMETRY_TRACE_FILE` either way — after
    set_trace_file(None) the env var is NOT re-consulted."""
    global _SINK_PATH, _SINK_FILE, _SINK_RESOLVED
    with _LOCK:
        if _SINK_FILE is not None:
            _SINK_FILE.close()
            _SINK_FILE = None
        _SINK_PATH = path
        _SINK_RESOLVED = True


def trace_file() -> Optional[str]:
    return _SINK_PATH


def _emit(ev: dict):
    global _SINK_PATH, _SINK_FILE, _SINK_RESOLVED
    with _LOCK:
        _RING.append(ev)
        if not _SINK_RESOLVED:
            _SINK_PATH = os.environ.get("PDT_TELEMETRY_TRACE_FILE") \
                or None
            _SINK_RESOLVED = True      # consult the env only once
        if _SINK_PATH is not None:
            if _SINK_FILE is None:
                _SINK_FILE = open(_SINK_PATH, "a", buffering=1)
            _SINK_FILE.write(json.dumps(ev) + "\n")


def events() -> List[dict]:
    """Snapshot of the ring buffer, oldest first."""
    with _LOCK:
        return list(_RING)


def clear():
    with _LOCK:
        _RING.clear()
    with _CARRIER_LOCK:
        _CARRIERS.clear()


# -- trace lifecycle ---------------------------------------------------
def start_trace(key: str, name: str = "trace.start",
                **attrs) -> Optional[int]:
    """Open a request-scoped trace: allocate a trace id, emit its root
    event (carrying `attrs` — include `request_id=key` so downstream
    joins and `request_tree()` find it), and register the carrier under
    `key`. Returns the trace id (None while telemetry is disabled).
    Re-opening a live key replaces the old carrier."""
    if not enabled():
        return None
    tid = next(_TRACE_IDS)
    seq = next(_SEQ)
    attrs.setdefault("request_id", key)
    with _CARRIER_LOCK:
        _CARRIERS[key] = (tid, seq)
        _CARRIERS.move_to_end(key)
        while len(_CARRIERS) > _CARRIER_CAP:
            _CARRIERS.popitem(last=False)
    t = _CLOCK()
    _emit({"name": name, "attrs": attrs, "ts": _wall(t), "ts_mono": t,
           "dur_s": 0.0, "seq": seq, "depth": 0, "parent": None,
           "trace": tid})
    return tid


def end_trace(key: str):
    """Drop the carrier for `key` (call once the request is terminal).
    Safe when absent; already-recorded events keep their trace id."""
    with _CARRIER_LOCK:
        _CARRIERS.pop(key, None)


def trace_of(key: str) -> Optional[int]:
    """Trace id registered for `key`, or None."""
    with _CARRIER_LOCK:
        ctx = _CARRIERS.get(key)
        return ctx[0] if ctx else None


def _carrier(key) -> Optional[tuple]:
    if not isinstance(key, str) or not _CARRIERS:
        return None
    with _CARRIER_LOCK:
        ctx = _CARRIERS.get(key)
        if ctx is not None:
            _CARRIERS.move_to_end(key)
        return ctx


@contextlib.contextmanager
def attach(key: str):
    """Join the trace registered for `key` explicitly: spans/events in
    the block parent under the trace root even without a `request_id`
    attr. Pass-through when telemetry is off or no carrier exists."""
    ctx = _carrier(key) if enabled() else None
    if ctx is None:
        yield
        return
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    frame = (ctx[1], ctx[0])               # (parent span seq, trace id)
    stack.append(frame)
    try:
        yield
    finally:
        if stack and stack[-1] is frame:
            stack.pop()
        elif frame in stack:               # unbalanced inner spans
            stack.remove(frame)


def _resolve_links(stack, attrs):
    """(parent seq, trace id, depth) for a new span/event: local
    nesting wins for the parent; the trace id comes from the enclosing
    frame or, failing that, from the carrier named by a `request_id`
    attr (the automatic router->replica->engine propagation)."""
    parent = stack[-1][0] if stack else None
    trace = stack[-1][1] if stack else None
    depth = len(stack)
    if trace is None:
        ctx = _carrier(attrs.get("request_id"))
        if ctx is not None:
            trace = ctx[0]
            if parent is None:
                parent = ctx[1]
                depth = 1
    return parent, trace, depth


class _NullSpan:
    """Disabled-mode span: no state, no clock reads, reusable."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("name", "attrs", "_t0", "_seq", "_depth",
                 "_parent", "_trace", "_rec")

    def __init__(self, name: str, attrs: Dict[str, object]):
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        stack = getattr(_TLS, "stack", None)
        if stack is None:
            stack = _TLS.stack = []
        self._seq = next(_SEQ)
        self._parent, self._trace, self._depth = _resolve_links(
            stack, self.attrs)
        stack.append((self._seq, self._trace))
        rec_cls = _record_event_cls()
        self._rec = None
        if rec_cls:
            try:
                self._rec = rec_cls(self.name)
                self._rec.begin()
            except Exception:
                self._rec = None       # profiler backend unavailable
        self._t0 = _CLOCK()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = _CLOCK() - self._t0
        if self._rec is not None:
            try:
                self._rec.end()
            except Exception:
                pass
        stack = _TLS.stack
        if stack and stack[-1][0] == self._seq:
            stack.pop()
        ev = {"name": self.name, "attrs": self.attrs,
              "ts": _wall(self._t0), "ts_mono": self._t0,
              "dur_s": dur, "seq": self._seq, "depth": self._depth,
              "parent": self._parent, "trace": self._trace}
        if exc_type is not None:
            ev["attrs"] = dict(self.attrs,
                               error=f"{exc_type.__name__}: {exc}")
        _emit(ev)
        return False


def span(name: str, **attrs):
    """`with span("serving.decode_step", slots=3): ...` — records one
    JSONL event on exit (duration, nesting, attrs; an escaping
    exception lands in `attrs["error"]`). A `request_id=` attr joins
    the request's distributed trace (module docstring). No-op while
    disabled."""
    if not enabled():
        return _NULL_SPAN
    return _Span(name, attrs)


def event(name: str, **attrs):
    """Point event (zero-duration span): fault fires, restarts,
    membership changes. A `request_id=` attr joins the request's
    distributed trace. No-op while disabled."""
    if not enabled():
        return
    stack = getattr(_TLS, "stack", None) or []
    parent, trace, depth = _resolve_links(stack, attrs)
    t = _CLOCK()
    _emit({"name": name, "attrs": attrs, "ts": _wall(t), "ts_mono": t,
           "dur_s": 0.0, "seq": next(_SEQ), "depth": depth,
           "parent": parent, "trace": trace})


# -- offline tooling ---------------------------------------------------
def load_trace_jsonl(path: str) -> List[dict]:
    """Read a `set_trace_file` JSONL sink back into an event list
    (blank lines skipped) for `request_tree` / `export_chrome_trace`."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def request_tree(request_id: str,
                 evts: Optional[List[dict]] = None) -> Optional[dict]:
    """Rebuild one request's span tree from the ring (or an event list
    / loaded JSONL): `{"event": root, "children": [...]}` nodes, each
    child list ordered by start time. Includes every event of the
    request's trace plus the batched decode steps that served it (a
    `serving.decode_step` span lists the request_ids it decoded for in
    its `rids` attr; those fan IN under the root). Returns None when no
    trace root for `request_id` exists in the events. With several
    roots for the same id (e.g. a refused submit retried later under a
    fresh trace), the NEWEST wins — it is the request's real serving
    timeline."""
    evts = events() if evts is None else evts
    root = None
    for e in evts:
        if e.get("parent") is None and e.get("trace") is not None \
                and (e.get("attrs") or {}).get("request_id") \
                == request_id:
            root = e                   # keep scanning: newest root wins
    if root is None:
        return None
    tid = root["trace"]
    nodes = {e["seq"]: {"event": e, "children": []}
             for e in evts if e.get("trace") == tid}
    for e in evts:
        rids = (e.get("attrs") or {}).get("rids") or ()
        if request_id in rids and e["seq"] not in nodes:
            nodes[e["seq"]] = {"event": e, "children": []}
    for seq in sorted(nodes):
        if seq == root["seq"]:
            continue
        node = nodes[seq]
        parent = nodes.get(node["event"].get("parent"))
        if parent is None or parent is node:
            parent = nodes[root["seq"]]    # fan-in (decode steps) or a
            # parent that fell off the bounded ring: keep the tree
            # connected under the root rather than dropping the node
        parent["children"].append(node)
    def _sort(node):
        node["children"].sort(
            key=lambda n: (n["event"].get("ts_mono",
                                          n["event"].get("ts", 0.0)),
                           n["event"]["seq"]))
        for c in node["children"]:
            _sort(c)
    _sort(nodes[root["seq"]])
    return nodes[root["seq"]]


def format_tree(tree: dict) -> str:
    """Human-readable rendering of a `request_tree` (operator CLI)."""
    lines: List[str] = []

    def walk(node, indent):
        e = node["event"]
        dur = e.get("dur_s", 0.0)
        tag = f" [{dur * 1e3:.2f}ms]" if dur else ""
        attrs = e.get("attrs") or {}
        extra = ""
        if "replica" in attrs and attrs["replica"] is not None:
            extra = f" replica={attrs['replica']}"
        if "error" in attrs:
            extra += f" error={attrs['error']!r}"
        lines.append(f"{'  ' * indent}{e['name']}{tag}{extra}")
        for c in node["children"]:
            walk(c, indent + 1)

    walk(tree, 0)
    return "\n".join(lines)


def export_chrome_trace(evts: Optional[List[dict]] = None,
                        path: Optional[str] = None) -> dict:
    """Render events as Chrome trace-event JSON (chrome://tracing and
    Perfetto both load it): pid = the replica that did the work (from
    the event's `replica` attr or the nearest ancestor span that has
    one), tid = the request (`request_id` attr; batched
    `serving.decode_step` spans fan OUT into one slice per request id
    in their `rids` attr). Spans are complete events (`ph="X"`, `dur`
    in microseconds), point events are instants (`ph="i"`), and
    process/thread names ride `ph="M"` metadata. Timestamps are
    microseconds on the shared monotonic base, rebased to the earliest
    event. Reads the live ring when `evts` is None; writes JSON to
    `path` when given; returns the trace document either way."""
    evts = events() if evts is None else list(evts)
    by_seq = {e["seq"]: e for e in evts if "seq" in e}

    def replica_of(e) -> Optional[object]:
        seen = set()
        while e is not None and e["seq"] not in seen:
            seen.add(e["seq"])
            r = (e.get("attrs") or {}).get("replica")
            if r is not None:
                return r
            e = by_seq.get(e.get("parent"))
        return None

    te: List[dict] = []
    pids: Dict[str, int] = {}
    tids: Dict[tuple, int] = {}

    def pid_for(label: str) -> int:
        if label not in pids:
            pids[label] = len(pids) + 1
            te.append({"ph": "M", "name": "process_name",
                       "pid": pids[label], "tid": 0,
                       "args": {"name": label}})
        return pids[label]

    def tid_for(pid: int, label: str) -> int:
        key = (pid, label)
        if key not in tids:
            tids[key] = len(tids) + 1
            te.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tids[key], "args": {"name": label}})
        return tids[key]

    base = min((e.get("ts_mono", e.get("ts", 0.0)) for e in evts),
               default=0.0)
    for e in evts:
        attrs = e.get("attrs") or {}
        replica = replica_of(e)
        pid = pid_for("host" if replica is None
                      else f"replica {replica}")
        if attrs.get("request_id") is not None:
            threads = [str(attrs["request_id"])]
        elif attrs.get("rids"):
            threads = [str(r) for r in attrs["rids"]]
        else:
            threads = ["engine"]
        args = dict(attrs)
        args.update(seq=e.get("seq"), trace=e.get("trace"),
                    parent=e.get("parent"))
        ts_us = (e.get("ts_mono", e.get("ts", 0.0)) - base) * 1e6
        dur_us = float(e.get("dur_s", 0.0)) * 1e6
        for th in threads:
            entry = {"name": e.get("name", "?"), "pid": pid,
                     "tid": tid_for(pid, th), "ts": round(ts_us, 3),
                     "args": args}
            if dur_us > 0:
                entry["ph"] = "X"
                entry["dur"] = round(dur_us, 3)
            else:
                entry["ph"] = "i"
                entry["s"] = "t"
            te.append(entry)
    doc = {"traceEvents": te, "displayTimeUnit": "ms"}
    if path is not None:
        with open(path, "w") as f:
            json.dump(doc, f)
            f.write("\n")
    return doc
