"""Metrics registry: typed Counter / Gauge / Histogram instruments.

Process-local, dependency-free (stdlib only — importable before jax).
Production TPU serving stacks tune against exactly these signals (TTFT,
per-step decode throughput, KV-page occupancy — PAPERS.md "Fine-Tuning
and Serving Gemma ... on Google Cloud TPU", "Ragged Paged Attention"),
so the instruments mirror the Prometheus data model 1:1:

* **Counter** — monotone float, `inc()` only.
* **Gauge** — settable float, `set()`/`inc()`/`dec()`.
* **Histogram** — fixed bucket boundaries chosen at creation
  (le-style cumulative export), plus running count/sum; `observe()`
  and a monotonic-clock `time()` context manager.

All three carry optional LABELS: an instrument declares its label
names once, every record call passes values for exactly those names,
and each distinct value combination is an independent series (keyed in
snapshots by the canonical Prometheus label string `a="x",b="y"`, or
`""` for unlabelled).

GUARANTEED NO-OP UNLESS ENABLED: recording methods return immediately —
touching no state, taking no lock — unless telemetry is on (env
`PDT_TELEMETRY=1`, read dynamically like `PDT_CHECK_INVARIANTS`, or a
programmatic `enable()` override). Instrument *creation* is always
allowed and idempotent (`registry.counter(name, ...)` get-or-creates),
so instrumented modules pay one dict lookup per call site and nothing
else when telemetry is off.

A single process-wide lock guards mutation: host-side instrumentation
sites (engine step loop, heartbeat daemon threads, launcher restarts)
are rare relative to device work, so a coarse lock is simpler than
per-series atomics and plenty fast.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, Iterable, Optional, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "Registry", "REGISTRY",
           "counter", "gauge", "histogram", "enable", "disable",
           "enabled", "reset", "snapshot", "value", "DEFAULT_BUCKETS"]

# latency buckets in seconds: sub-ms host ops up through multi-minute
# checkpoint writes, +Inf implied
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

_LOCK = threading.RLock()

# None -> env-driven; True/False -> programmatic override (enable()/
# disable() win over the environment either way)
_ENABLED_OVERRIDE: Optional[bool] = None


def enabled() -> bool:
    """Is telemetry recording on? `enable()`/`disable()` override the
    environment; otherwise `PDT_TELEMETRY=1` decides (read dynamically
    so test fixtures can flip it per-module)."""
    if _ENABLED_OVERRIDE is not None:
        return _ENABLED_OVERRIDE
    return os.environ.get("PDT_TELEMETRY") == "1"


def enable():
    """Turn telemetry on for this process (wins over the env var)."""
    global _ENABLED_OVERRIDE
    _ENABLED_OVERRIDE = True


def disable(clear_override: bool = False):
    """Turn telemetry off. With `clear_override=True` the decision
    returns to the `PDT_TELEMETRY` env var instead of a hard off."""
    global _ENABLED_OVERRIDE
    _ENABLED_OVERRIDE = None if clear_override else False


def _label_key(labelnames: Tuple[str, ...], labels: Dict[str, str]) \
        -> Tuple[str, ...]:
    if set(labels) != set(labelnames):
        raise ValueError(
            f"expected labels {labelnames}, got {tuple(sorted(labels))}")
    return tuple(str(labels[n]) for n in labelnames)


def _escape_label_value(v: str) -> str:
    """Prometheus exposition escaping: backslash, double-quote, and
    newline must be escaped or a value like `a"b` (e.g. a --job_id fed
    straight into a label) corrupts the scrape text."""
    return v.replace("\\", r"\\").replace('"', r'\"').replace("\n",
                                                              r"\n")


def _label_string(labelnames: Tuple[str, ...],
                  values: Tuple[str, ...]) -> str:
    """Canonical Prometheus label body: `a="x",b="y"` (no braces,
    values escaped), `""` for the unlabelled series — the
    snapshot/export key."""
    return ",".join(f'{n}="{_escape_label_value(v)}"'
                    for n, v in zip(labelnames, values))


class _Instrument:
    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Iterable[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        # label-values tuple -> series state (float, or histogram dict)
        self._series: Dict[Tuple[str, ...], object] = {}

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        return _label_key(self.labelnames, labels)

    def clear(self):
        with _LOCK:
            self._series.clear()

    def remove(self, **labels):
        """Drop one series (e.g. a departed worker's gauge) so snapshots
        and exports stop reporting a frozen last value. Safe when the
        series is absent, and NOT gated on enabled() — retiring stale
        state is cleanup, not recording."""
        with _LOCK:
            self._series.pop(self._key(labels), None)


class Counter(_Instrument):
    """Monotonically increasing value (Prometheus counter)."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels):
        if not enabled():
            return
        if amount < 0:
            raise ValueError(f"counter {self.name}: inc by {amount} < 0")
        key = self._key(labels)
        with _LOCK:
            self._series[key] = self._series.get(key, 0.0) + amount

    def get(self, **labels) -> float:
        return float(self._series.get(self._key(labels), 0.0))


class Gauge(_Instrument):
    """Settable point-in-time value (Prometheus gauge)."""

    kind = "gauge"

    def set(self, v: float, **labels):
        if not enabled():
            return
        with _LOCK:
            self._series[self._key(labels)] = float(v)

    def inc(self, amount: float = 1.0, **labels):
        if not enabled():
            return
        key = self._key(labels)
        with _LOCK:
            self._series[key] = self._series.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels):
        self.inc(-amount, **labels)

    def get(self, **labels) -> float:
        return float(self._series.get(self._key(labels), 0.0))


class _Timer:
    """Monotonic-clock span feeding one histogram observation; usable
    as a context manager or via explicit stop()."""

    def __init__(self, hist: "Histogram", labels: Dict[str, str]):
        self._hist = hist
        self._labels = labels
        self._t0 = time.perf_counter()

    def stop(self) -> float:
        dt = time.perf_counter() - self._t0
        self._hist.observe(dt, **self._labels)
        return dt

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class Histogram(_Instrument):
    """Fixed-boundary histogram (Prometheus le-bucket export)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 labelnames: Iterable[str] = (),
                 buckets: Iterable[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError(f"histogram {name}: needs >= 1 bucket")
        self.buckets = bs                     # +Inf implied

    def observe(self, v: float, exemplar: Optional[str] = None,
                **labels):
        """Record one observation. `exemplar` (e.g. a request/trace id)
        is remembered as the MOST RECENT exemplar of whichever bucket
        the value lands in — OpenMetrics exemplar semantics, so a p99
        bucket in the export links straight to a concrete trace."""
        if not enabled():
            return
        v = float(v)
        key = self._key(labels)
        with _LOCK:
            s = self._series.get(key)
            if s is None:
                s = {"count": 0, "sum": 0.0,
                     "counts": [0] * (len(self.buckets) + 1)}
                self._series[key] = s
            s["count"] += 1
            s["sum"] += v
            # non-cumulative per-bucket counts; cumulated at export
            for i, b in enumerate(self.buckets):
                if v <= b:
                    s["counts"][i] += 1
                    break
            else:
                i = len(self.buckets)
                s["counts"][-1] += 1          # +Inf bucket
            if exemplar is not None:
                # keyed by bucket INDEX internally; snapshot renders
                # the le-boundary string ("exemplars" key only when one
                # was ever recorded, preserving export round-trip)
                s.setdefault("exemplars", {})[i] = {
                    "trace_id": str(exemplar), "value": v}

    def time(self, **labels) -> _Timer:
        """Context manager timing its body on the monotonic clock."""
        return _Timer(self, labels)

    def get(self, **labels) -> Dict[str, float]:
        """{"count", "sum"} for the series (0s when never observed)."""
        s = self._series.get(self._key(labels))
        if s is None:
            return {"count": 0, "sum": 0.0}
        return {"count": s["count"], "sum": s["sum"]}


class Registry:
    """Name -> instrument map with get-or-create accessors. Creation is
    idempotent; re-declaring a name with a different kind/labels/buckets
    raises (two call sites disagreeing about an instrument is a bug,
    not a merge)."""

    def __init__(self):
        self._instruments: Dict[str, _Instrument] = {}

    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with _LOCK:
            inst = self._instruments.get(name)
            if inst is None:
                inst = cls(name, help, labelnames, **kw)
                self._instruments[name] = inst
                return inst
            if not isinstance(inst, cls) or type(inst) is not cls:
                raise ValueError(
                    f"instrument {name!r} already registered as "
                    f"{inst.kind}, not {cls.kind}")
            if inst.labelnames != tuple(labelnames):
                raise ValueError(
                    f"instrument {name!r} already registered with labels "
                    f"{inst.labelnames}, not {tuple(labelnames)}")
            if kw.get("buckets") is not None and isinstance(
                    inst, Histogram) and inst.buckets != tuple(
                    sorted(float(b) for b in kw["buckets"])):
                raise ValueError(
                    f"histogram {name!r} already registered with "
                    f"buckets {inst.buckets}")
            return inst

    def counter(self, name: str, help: str = "",
                labelnames: Iterable[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, tuple(labelnames))

    def gauge(self, name: str, help: str = "",
              labelnames: Iterable[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, tuple(labelnames))

    def histogram(self, name: str, help: str = "",
                  labelnames: Iterable[str] = (),
                  buckets: Iterable[float] = DEFAULT_BUCKETS) \
            -> Histogram:
        return self._get_or_create(Histogram, name, help,
                                   tuple(labelnames), buckets=buckets)

    def instruments(self) -> Dict[str, _Instrument]:
        with _LOCK:
            return dict(self._instruments)

    def reset(self):
        """Zero every series (instruments stay registered — their call
        sites hold references). Test isolation + scrape-epoch resets."""
        with _LOCK:
            for inst in self._instruments.values():
                inst.clear()

    def snapshot(self) -> Dict[str, object]:
        """JSON-safe dump of every live series. Histogram buckets are
        CUMULATIVE keyed by the le boundary (Prometheus semantics), so
        a parsed text exposition compares equal to this directly."""
        out = {"enabled": enabled(),
               "counters": {}, "gauges": {}, "histograms": {}}
        with _LOCK:
            for name, inst in sorted(self._instruments.items()):
                if not inst._series:
                    continue      # never recorded: absent, not {} — the
                    # text exposition skips it too, so parse-back of the
                    # export compares equal to this snapshot
                if isinstance(inst, Histogram):
                    dst = out["histograms"].setdefault(name, {})
                    for key, s in sorted(inst._series.items()):
                        cum, bmap = 0, {}
                        for b, c in zip(inst.buckets, s["counts"]):
                            cum += c
                            bmap[_fmt_float(b)] = cum
                        bmap["+Inf"] = s["count"]
                        entry = {"count": s["count"], "sum": s["sum"],
                                 "buckets": bmap}
                        ex = s.get("exemplars")
                        if ex:
                            les = [_fmt_float(b)
                                   for b in inst.buckets] + ["+Inf"]
                            entry["exemplars"] = {
                                les[i]: dict(e)
                                for i, e in sorted(ex.items())}
                        dst[_label_string(inst.labelnames, key)] = entry
                elif isinstance(inst, (Counter, Gauge)):
                    dst = out["counters" if isinstance(inst, Counter)
                              else "gauges"].setdefault(name, {})
                    for key, v in sorted(inst._series.items()):
                        dst[_label_string(inst.labelnames, key)] = v
        return out


def _fmt_float(v: float) -> str:
    """Round-trippable number formatting shared by snapshot and the
    text exposition: integers render bare (`3`, not `3.0`) so the
    golden test output stays readable, everything else via repr."""
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


REGISTRY = Registry()


# module-level conveniences bound to the global registry --------------
def counter(name: str, help: str = "",
            labelnames: Iterable[str] = ()) -> Counter:
    return REGISTRY.counter(name, help, labelnames)


def gauge(name: str, help: str = "",
          labelnames: Iterable[str] = ()) -> Gauge:
    return REGISTRY.gauge(name, help, labelnames)


def histogram(name: str, help: str = "",
              labelnames: Iterable[str] = (),
              buckets: Iterable[float] = DEFAULT_BUCKETS) -> Histogram:
    return REGISTRY.histogram(name, help, labelnames, buckets=buckets)


def reset():
    REGISTRY.reset()


def snapshot() -> Dict[str, object]:
    return REGISTRY.snapshot()


def value(name: str, **labels) -> float:
    """Current value of a counter/gauge series (0.0 when absent) — the
    one-liner tests reach for when reconciling engine counters."""
    inst = REGISTRY.instruments().get(name)
    if inst is None:
        return 0.0
    if isinstance(inst, Histogram):
        raise TypeError(f"{name!r} is a histogram — use "
                        "snapshot()['histograms'] or .get()")
    return inst.get(**labels)
