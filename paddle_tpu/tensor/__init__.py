"""Op surface assembly: exports every op and attaches the method/operator
surface onto Tensor. ≙ reference «python/paddle/tensor/__init__.py» method
registration (`tensor_method_func` monkey-patching) [U]."""
from __future__ import annotations

from ..core.tensor import Tensor, Parameter, to_tensor

from . import attribute, creation, einsum as _einsum_mod, linalg, logic, \
    manipulation, math, random, search, stat

from .attribute import *  # noqa: F401,F403
from .creation import *  # noqa: F401,F403
from .einsum import einsum  # noqa: F401
from .logic import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403

# names that collide with python builtins are still exported (paddle does this)
from .math import abs, all, any, max, min, pow, round, sum  # noqa: F401
from .manipulation import slice  # noqa: F401

# linalg ops that paddle also exposes at top level
from .linalg import (norm, dist, cholesky, matrix_power, pinv,  # noqa: F401
                     tensordot)
from .manipulation import t  # noqa: F401

_METHOD_SOURCES = [math, manipulation, logic, search, stat, linalg, attribute,
                   creation, random]

# ops attached as Tensor methods (tensor-first signature)
_METHOD_NAMES = [
    # math
    "exp", "expm1", "log", "log2", "log10", "log1p", "sqrt", "rsqrt",
    "square", "abs", "sign", "neg", "reciprocal", "sin", "cos", "tan",
    "asin", "acos", "atan", "sinh", "cosh", "tanh", "asinh", "acosh",
    "atanh", "floor", "ceil", "round", "trunc", "frac", "erf", "erfinv",
    "sigmoid", "digamma", "lgamma", "conj", "real", "imag", "angle",
    "deg2rad", "rad2deg", "add", "subtract", "multiply", "divide",
    "floor_divide", "mod", "remainder", "pow", "maximum", "minimum",
    "fmax", "fmin", "atan2", "logaddexp", "heaviside", "gcd", "lcm",
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
    "scale", "clip", "lerp", "nan_to_num", "stanh",
    "sum", "mean", "prod", "max", "min", "amax", "amin", "nansum",
    "nanmean", "logsumexp", "all", "any", "count_nonzero", "cumsum",
    "cumprod", "cummax", "cummin", "logcumsumexp", "matmul", "mm", "bmm",
    "dot", "inner", "outer", "mv", "kron", "cross", "trace", "diagonal",
    "diff", "isfinite", "isinf", "isnan", "isclose", "allclose",
    "equal_all", "take", "trapezoid", "frexp", "signbit", "multiplex",
    "addmm", "increment",
    # manipulation
    "reshape", "reshape_", "flatten", "squeeze", "unsqueeze", "transpose",
    "moveaxis", "swapaxes", "t", "concat", "split", "chunk", "tensor_split",
    "gather", "gather_nd", "take_along_axis", "put_along_axis", "scatter",
    "scatter_", "scatter_nd_add", "index_select", "index_sample",
    "index_add", "index_put", "index_fill", "tile", "expand", "expand_as",
    "broadcast_to", "flip", "rot90", "roll", "repeat_interleave", "unbind",
    "unique", "unique_consecutive", "masked_select", "masked_fill",
    "masked_scatter", "where", "nonzero", "unstack", "strided_slice",
    "view", "view_as", "as_strided", "unflatten", "unfold", "bincount",
    "histogram", "cdist", "as_complex", "as_real", "pad",
    # logic
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "logical_and", "logical_or", "logical_xor", "logical_not",
    "is_empty", "isin",
    # search
    "argmax", "argmin", "argsort", "sort", "topk", "searchsorted",
    "bucketize", "kthvalue", "mode",
    # stat
    "var", "std", "median", "nanmedian", "quantile", "nanquantile",
    # linalg
    "norm", "det", "inv", "pinv", "cholesky", "qr", "svd", "eigvals",
    "matrix_power", "dist",
    # attribute
    "rank", "numel", "is_floating_point", "is_complex", "is_integer",
    # creation
    "tril", "triu", "diag",
    # round-3 breadth
    "float_power", "positive", "isposinf", "isneginf", "isreal",
    "gammainc", "gammaincc", "cumulative_trapezoid", "vecdot",
    "histogram_bin_edges", "bitwise_invert", "diagonal_scatter",
    "select_scatter", "slice_scatter", "sgn", "sinc", "pdist", "renorm",
    "vander", "combinations", "polygamma", "gammaln",
    # round-4 breadth (Tensor-method audit closers)
    "arccos", "arcsin", "arctan", "arccosh", "arcsinh", "arctanh",
    "reverse", "logit", "multinomial", "slice", "stack", "tensordot",
    "inverse", "is_tensor", "shard_index",
]


def _attach_methods():
    for name in _METHOD_NAMES:
        fn = None
        for mod in _METHOD_SOURCES:
            fn = getattr(mod, name, None)
            if callable(fn):
                break
        if fn is None:
            raise RuntimeError(f"tensor method {name!r} not found")
        if not hasattr(Tensor, name):
            setattr(Tensor, name, fn)

    # in-place variants: paddle `op_`(x, ...) == x = op(x, ...)
    def _make_inplace(fname):
        base = getattr(Tensor, fname)

        def method(self, *args, **kwargs):
            self._assign_inplace(base(self, *args, **kwargs))
            return self
        method.__name__ = fname + "_"
        return method

    for fname in ["add", "subtract", "multiply", "divide", "clip", "scale",
                  "exp", "sqrt", "rsqrt", "floor", "ceil", "round", "abs",
                  "sin", "cos", "tanh", "sigmoid", "reciprocal", "flatten",
                  "squeeze", "unsqueeze", "transpose", "tril", "triu",
                  "masked_fill", "index_fill", "put_along_axis", "lerp",
                  "pow", "remainder", "mod", "logical_and", "logical_or",
                  "logical_xor", "logical_not", "where", "trunc", "frac",
                  "gcd", "lcm", "hypot", "nan_to_num", "index_add",
                  "erfinv", "neg"]:
        iname = fname + "_"
        if not hasattr(Tensor, iname) and hasattr(Tensor, fname):
            setattr(Tensor, iname, _make_inplace(fname))

    def zero_(self):
        import jax.numpy as jnp
        self._value = jnp.zeros_like(self._value)
        self._node = None
        return self

    def fill_(self, value):
        import jax.numpy as jnp
        self._value = jnp.full_like(self._value, value)
        self._node = None
        return self

    def fill_diagonal_(self, value, offset=0, wrap=False):
        import builtins
        import jax.numpy as jnp
        v = self._value
        if v.ndim < 2:
            raise ValueError("fill_diagonal_ needs ndim >= 2")
        if v.ndim > 2:
            # paddle semantics for >2-D: all dims equal, fill x[i,...,i]
            if len(set(v.shape)) != 1:
                raise ValueError(
                    "fill_diagonal_ on ndim > 2 requires all dims equal")
            if offset:
                raise ValueError("offset is 2-D only")
            i = jnp.arange(v.shape[0])
            self._value = v.at[(i,) * v.ndim].set(value)
            self._node = None
            return self
        r, c = v.shape
        # builtins: the module-level min/max are paddle's reductions
        ln = builtins.max(builtins.min(r - builtins.max(-offset, 0),
                                       c - builtins.max(offset, 0)), 0)
        i = jnp.arange(ln)
        v = v.at[i + builtins.max(-offset, 0),
                 i + builtins.max(offset, 0)].set(value)
        if wrap and r > c and offset == 0:
            # numpy wrap semantics: every (C+1)th flat element
            flat = v.reshape(-1).at[jnp.arange(0, r * c, c + 1)]                 .set(value)
            v = flat.reshape(r, c)
        self._value = v
        self._node = None
        return self

    def pin_memory(self):
        return self          # host/device staging is XLA's job on TPU

    def softmax(self, axis=-1):
        from ..nn import functional as F
        return F.softmax(self, axis)

    def lu(self, pivot=True, get_infos=False, name=None):
        from .. import linalg as _linalg
        return _linalg.lu(self, pivot=pivot, get_infos=get_infos)

    Tensor.lu = lu
    Tensor.fill_diagonal_ = fill_diagonal_
    Tensor.pin_memory = pin_memory
    Tensor.softmax = softmax
    Tensor.zero_ = zero_
    Tensor.fill_ = fill_
    Tensor.uniform_ = random.uniform_
    Tensor.normal_ = random.normal_
    Tensor.exponential_ = random.exponential_
    Tensor.bernoulli_ = random.bernoulli_
    Tensor.cast = Tensor.astype

    # -- operator dunders ----------------------------------------------------
    Tensor.__add__ = lambda s, o: math.add(s, o)
    Tensor.__radd__ = lambda s, o: math.add(o, s)
    Tensor.__sub__ = lambda s, o: math.subtract(s, o)
    Tensor.__rsub__ = lambda s, o: math.subtract(o, s)
    Tensor.__mul__ = lambda s, o: math.multiply(s, o)
    Tensor.__rmul__ = lambda s, o: math.multiply(o, s)
    Tensor.__truediv__ = lambda s, o: math.divide(s, o)
    Tensor.__rtruediv__ = lambda s, o: math.divide(o, s)
    Tensor.__floordiv__ = lambda s, o: math.floor_divide(s, o)
    Tensor.__rfloordiv__ = lambda s, o: math.floor_divide(o, s)
    Tensor.__mod__ = lambda s, o: math.mod(s, o)
    Tensor.__rmod__ = lambda s, o: math.mod(o, s)
    Tensor.__pow__ = lambda s, o: math.pow(s, o)
    Tensor.__rpow__ = lambda s, o: math.pow(o, s)
    Tensor.__matmul__ = lambda s, o: math.matmul(s, o)
    Tensor.__rmatmul__ = lambda s, o: math.matmul(o, s)
    Tensor.__eq__ = lambda s, o: logic.equal(s, o)
    Tensor.__ne__ = lambda s, o: logic.not_equal(s, o)
    Tensor.__lt__ = lambda s, o: logic.less_than(s, o)
    Tensor.__le__ = lambda s, o: logic.less_equal(s, o)
    Tensor.__gt__ = lambda s, o: logic.greater_than(s, o)
    Tensor.__ge__ = lambda s, o: logic.greater_equal(s, o)
    Tensor.__and__ = lambda s, o: math.bitwise_and(s, o)
    Tensor.__or__ = lambda s, o: math.bitwise_or(s, o)
    Tensor.__xor__ = lambda s, o: math.bitwise_xor(s, o)
    Tensor.__invert__ = lambda s: math.bitwise_not(s)
    Tensor.__lshift__ = lambda s, o: math.bitwise_left_shift(s, o)
    Tensor.__rshift__ = lambda s, o: math.bitwise_right_shift(s, o)
    # iadd etc. keep tape semantics via _assign_inplace
    def _imake(opfn):
        def im(self, other):
            self._assign_inplace(opfn(self, other))
            return self
        return im
    Tensor.__iadd__ = _imake(math.add)
    Tensor.__isub__ = _imake(math.subtract)
    Tensor.__imul__ = _imake(math.multiply)
    Tensor.__itruediv__ = _imake(math.divide)


_attach_methods()
