"""Comparison & logical ops. ≙ reference «python/paddle/tensor/logic.py» [U]."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor, apply, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _cmp(op_name, jfn):
    def op(x, y, name=None):
        xt, yt = isinstance(x, Tensor), isinstance(y, Tensor)
        if xt and yt:
            return apply(op_name, jfn, (x, y))
        if xt:
            return apply(op_name, lambda v: jfn(v, y), (x,))
        if yt:
            return apply(op_name, lambda v: jfn(x, v), (y,))
        return apply(op_name, jfn, (_t(x), _t(y)))
    op.__name__ = op_name
    return op


equal = _cmp("equal", jnp.equal)
not_equal = _cmp("not_equal", jnp.not_equal)
greater_than = _cmp("greater_than", jnp.greater)
greater_equal = _cmp("greater_equal", jnp.greater_equal)
less_than = _cmp("less_than", jnp.less)
less_equal = _cmp("less_equal", jnp.less_equal)
logical_and = _cmp("logical_and", jnp.logical_and)
logical_or = _cmp("logical_or", jnp.logical_or)
logical_xor = _cmp("logical_xor", jnp.logical_xor)


def logical_not(x, out=None, name=None):
    return apply("logical_not", jnp.logical_not, (_t(x),))


def is_empty(x, name=None):
    return Tensor(jnp.asarray(_t(x).size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    tv = _t(test_x)._value
    return apply("isin",
                 lambda v: jnp.isin(v, tv, invert=invert), (_t(x),))
