"""einsum. ≙ reference «python/paddle/tensor/einsum.py» [U] — delegates to
XLA's dot_general-based jnp.einsum (MXU-friendly)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor, apply, to_tensor


def einsum(equation, *operands, **kwargs):
    ts = tuple(o if isinstance(o, Tensor) else to_tensor(o) for o in operands)
    return apply("einsum", lambda *vs: jnp.einsum(equation, *vs), ts)
