"""Shape/layout manipulation ops. ≙ reference
«python/paddle/tensor/manipulation.py» [U]. All static-shape → XLA-friendly;
ops whose output shape is data-dependent (`masked_select`, `nonzero`) return
host-synced results and are documented as eager-only."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core.tensor import Tensor, apply, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return [int(s) for s in shape.tolist()]
    return [int(s._value) if isinstance(s, Tensor) else int(s) for s in shape]


def reshape(x, shape, name=None):
    s = _shape_list(shape)
    return apply("reshape", lambda v: jnp.reshape(v, s), (_t(x),))


def reshape_(x, shape, name=None):
    x._assign_inplace(reshape(x, shape)); return x


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def fn(v):
        nd = v.ndim
        a = start_axis % nd if nd else 0
        b = stop_axis % nd if nd else 0
        new_shape = v.shape[:a] + (-1,) + v.shape[b + 1:]
        return v.reshape(new_shape)
    return apply("flatten", fn, (_t(x),))


def squeeze(x, axis=None, name=None):
    def fn(v):
        if axis is None:
            return jnp.squeeze(v)
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        axes = tuple(a % v.ndim for a in axes if v.shape[a % v.ndim] == 1)
        return jnp.squeeze(v, axis=axes) if axes else v
    return apply("squeeze", fn, (_t(x),))


def unsqueeze(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    axes = [int(a._value) if isinstance(a, Tensor) else int(a) for a in axes]

    def fn(v):
        out = v
        for a in sorted(axes):
            out = jnp.expand_dims(out, a)
        return out
    return apply("unsqueeze", fn, (_t(x),))


def transpose(x, perm=None, name=None):
    p = [int(i) for i in perm] if perm is not None else None
    return apply("transpose", lambda v: jnp.transpose(v, p), (_t(x),))


def moveaxis(x, source, destination, name=None):
    return apply("moveaxis", lambda v: jnp.moveaxis(v, source, destination),
                 (_t(x),))


def swapaxes(x, axis1, axis2, name=None):
    return apply("swapaxes", lambda v: jnp.swapaxes(v, axis1, axis2), (_t(x),))


transpose_ = None
def t(input, name=None):
    return apply("t", lambda v: v.T if v.ndim >= 2 else v, (_t(input),))


def concat(x, axis=0, name=None):
    ax = int(axis._value) if isinstance(axis, Tensor) else int(axis)
    ts = tuple(_t(i) for i in x)
    dt = ts[0]._value.dtype
    for u in ts[1:]:
        dt = jnp.promote_types(dt, u._value.dtype)
    return apply("concat", lambda *vs: jnp.concatenate(
        [v.astype(dt) for v in vs], axis=ax), ts)


def stack(x, axis=0, name=None):
    ts = tuple(_t(i) for i in x)
    return apply("stack", lambda *vs: jnp.stack(vs, axis=axis), ts)


def unstack(x, axis=0, num=None, name=None):
    x = _t(x)
    n = num if num is not None else x.shape[axis]
    return apply("unstack",
                 lambda v: tuple(jnp.squeeze(s, axis)
                                 for s in jnp.split(v, n, axis)),
                 (x,), multi_output=True)


def split(x, num_or_sections, axis=0, name=None):
    x = _t(x)
    ax = int(axis._value) if isinstance(axis, Tensor) else int(axis)

    if isinstance(num_or_sections, int):
        n = num_or_sections
        return apply("split", lambda v: tuple(jnp.split(v, n, ax)), (x,),
                     multi_output=True)
    secs = [int(s._value) if isinstance(s, Tensor) else int(s)
            for s in num_or_sections]
    total = x.shape[ax]
    n_unknown = builtins_sum(1 for s in secs if s < 0)
    if n_unknown:
        known = builtins_sum(s for s in secs if s >= 0)
        secs = [s if s >= 0 else total - known for s in secs]
    splits = np.cumsum(secs)[:-1].tolist()
    return apply("split", lambda v: tuple(jnp.split(v, splits, ax)), (x,),
                 multi_output=True)


def builtins_sum(it, start=0):
    total = start
    for v in it:
        total = total + v
    return total


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def tensor_split(x, num_or_indices, axis=0, name=None):
    x = _t(x)
    return apply("tensor_split",
                 lambda v: tuple(jnp.array_split(v, num_or_indices, axis)),
                 (x,), multi_output=True)


def slice(input, axes, starts, ends):
    axes = [int(a) for a in axes]
    starts = [int(s._value) if isinstance(s, Tensor) else int(s) for s in starts]
    ends = [int(e._value) if isinstance(e, Tensor) else int(e) for e in ends]

    def fn(v):
        idx = [builtins_slice(None)] * v.ndim
        for a, s, e in zip(axes, starts, ends):
            idx[a] = builtins_slice(s, e)
        return v[tuple(idx)]
    return apply("slice", fn, (_t(input),))


import builtins as _bi
builtins_slice = _bi.slice


def strided_slice(x, axes, starts, ends, strides, name=None):
    def fn(v):
        idx = [_bi.slice(None)] * v.ndim
        for a, s, e, st in zip(axes, starts, ends, strides):
            idx[int(a)] = _bi.slice(int(s), int(e), int(st))
        return v[tuple(idx)]
    return apply("strided_slice", fn, (_t(x),))


def gather(x, index, axis=0, name=None):
    idx = index._value if isinstance(index, Tensor) else jnp.asarray(index)
    ax = int(axis._value) if isinstance(axis, Tensor) else int(axis)
    return apply("gather", lambda v: jnp.take(v, idx.reshape(-1) if idx.ndim
                                              else idx, axis=ax), (_t(x),))


def gather_nd(x, index, name=None):
    idx = index._value if isinstance(index, Tensor) else jnp.asarray(index)

    def fn(v):
        k = idx.shape[-1]
        return v[tuple(jnp.moveaxis(idx, -1, 0))] if k > 0 else v
    return apply("gather_nd", fn, (_t(x),))


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    idx = indices._value if isinstance(indices, Tensor) else jnp.asarray(indices)

    def fn(v):
        i = idx
        if broadcast:
            tgt = list(v.shape)
            tgt[axis] = i.shape[axis]
            i = jnp.broadcast_to(i, tgt)
        return jnp.take_along_axis(v, i, axis=axis)
    return apply("take_along_axis", fn, (_t(arr),))


def put_along_axis(arr, indices, values, axis, reduce="assign",
                   include_self=True, broadcast=True, name=None):
    idx = indices._value if isinstance(indices, Tensor) else jnp.asarray(indices)
    mode = reduce

    def fn(v, val):
        val = jnp.broadcast_to(val, idx.shape).astype(v.dtype)
        dims = list(range(v.ndim))
        grids = jnp.meshgrid(*[jnp.arange(s) for s in idx.shape], indexing="ij")
        full_idx = tuple(idx if d == axis % v.ndim else grids[d] for d in dims)
        a = v.at[full_idx]
        if mode == "assign":
            return a.set(val)
        if mode in ("add", "sum"):
            return a.add(val)
        if mode in ("mul", "multiply"):
            return a.multiply(val)
        if mode == "amax":
            return a.max(val)
        if mode == "amin":
            return a.min(val)
        if mode == "mean":
            ones = jnp.zeros(v.shape, jnp.float32).at[full_idx].add(1.0)
            summed = v.at[full_idx].add(val)
            cnt = jnp.maximum(ones + 1.0, 1.0)
            return jnp.where(ones > 0, (summed / cnt).astype(v.dtype), v)
        raise ValueError(f"unknown reduce mode {mode}")
    vt = values if isinstance(values, Tensor) else to_tensor(values)
    return apply("put_along_axis", fn, (_t(arr), vt))


def scatter(x, index, updates, overwrite=True, name=None):
    idx = index._value if isinstance(index, Tensor) else jnp.asarray(index)

    def fn(v, u):
        u = u.astype(v.dtype)
        if overwrite:
            return v.at[idx].set(u)
        # paddle: overwrite=False accumulates after zeroing target rows
        z = v.at[idx].set(jnp.zeros_like(u))
        return z.at[idx].add(u)
    ut = updates if isinstance(updates, Tensor) else to_tensor(updates)
    return apply("scatter", fn, (_t(x), ut))


def scatter_(x, index, updates, overwrite=True, name=None):
    x._assign_inplace(scatter(x, index, updates, overwrite)); return x


def scatter_nd(index, updates, shape, name=None):
    idx = index._value if isinstance(index, Tensor) else jnp.asarray(index)
    s = _shape_list(shape)

    def fn(u):
        z = jnp.zeros(s, u.dtype)
        return z.at[tuple(jnp.moveaxis(idx, -1, 0))].add(u)
    ut = updates if isinstance(updates, Tensor) else to_tensor(updates)
    return apply("scatter_nd", fn, (ut,))


def scatter_nd_add(x, index, updates, name=None):
    idx = index._value if isinstance(index, Tensor) else jnp.asarray(index)

    def fn(v, u):
        return v.at[tuple(jnp.moveaxis(idx, -1, 0))].add(u.astype(v.dtype))
    ut = updates if isinstance(updates, Tensor) else to_tensor(updates)
    return apply("scatter_nd_add", fn, (_t(x), ut))


def index_select(x, index, axis=0, name=None):
    idx = index._value if isinstance(index, Tensor) else jnp.asarray(index)
    return apply("index_select", lambda v: jnp.take(v, idx, axis=axis), (_t(x),))


def index_sample(x, index):
    idx = index._value if isinstance(index, Tensor) else jnp.asarray(index)
    return apply("index_sample",
                 lambda v: jnp.take_along_axis(v, idx, axis=1), (_t(x),))


def index_add(x, index, axis, value, name=None):
    idx = index._value if isinstance(index, Tensor) else jnp.asarray(index)

    def fn(v, val):
        perm_v = jnp.moveaxis(v, axis, 0)
        perm_val = jnp.moveaxis(val.astype(v.dtype), axis, 0)
        out = perm_v.at[idx].add(perm_val)
        return jnp.moveaxis(out, 0, axis)
    vt = value if isinstance(value, Tensor) else to_tensor(value)
    return apply("index_add", fn, (_t(x), vt))


def index_put(x, indices, value, accumulate=False, name=None):
    idx = tuple(i._value if isinstance(i, Tensor) else jnp.asarray(i)
                for i in indices)

    def fn(v, val):
        a = v.at[idx]
        return a.add(val.astype(v.dtype)) if accumulate \
            else a.set(val.astype(v.dtype))
    vt = value if isinstance(value, Tensor) else to_tensor(value)
    return apply("index_put", fn, (_t(x), vt))


def index_fill(x, index, axis, value, name=None):
    idx = index._value if isinstance(index, Tensor) else jnp.asarray(index)

    def fn(v):
        perm_v = jnp.moveaxis(v, axis, 0)
        out = perm_v.at[idx].set(jnp.asarray(value, v.dtype))
        return jnp.moveaxis(out, 0, axis)
    return apply("index_fill", fn, (_t(x),))


def tile(x, repeat_times, name=None):
    reps = _shape_list(repeat_times)
    return apply("tile", lambda v: jnp.tile(v, reps), (_t(x),))


def expand(x, shape, name=None):
    s = _shape_list(shape)

    def fn(v):
        tgt = list(s)
        # -1 means keep original dim
        off = len(tgt) - v.ndim
        for i in range(len(tgt)):
            if tgt[i] == -1:
                tgt[i] = v.shape[i - off]
        return jnp.broadcast_to(v, tgt)
    return apply("expand", fn, (_t(x),))


def expand_as(x, y, name=None):
    tgt = tuple(_t(y)._value.shape)
    return apply("expand_as", lambda v: jnp.broadcast_to(v, tgt), (_t(x),))


def broadcast_to(x, shape, name=None):
    s = tuple(_shape_list(shape))
    return apply("broadcast_to", lambda v: jnp.broadcast_to(v, s), (_t(x),))


def broadcast_tensors(input, name=None):
    ts = tuple(_t(i) for i in input)
    return apply("broadcast_tensors",
                 lambda *vs: tuple(jnp.broadcast_arrays(*vs)), ts,
                 multi_output=True)


def flip(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return apply("flip", lambda v: jnp.flip(v, axis=tuple(axes)), (_t(x),))


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply("rot90", lambda v: jnp.rot90(v, k=k, axes=tuple(axes)), (_t(x),))


def roll(x, shifts, axis=None, name=None):
    sh = tuple(shifts) if isinstance(shifts, (list, tuple)) else shifts
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply("roll", lambda v: jnp.roll(v, sh, axis=ax), (_t(x),))


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        reps = repeats._value
        total = int(np.asarray(reps).sum())
        return apply("repeat_interleave",
                     lambda v: jnp.repeat(v, reps, axis=axis,
                                          total_repeat_length=total), (_t(x),))
    return apply("repeat_interleave",
                 lambda v: jnp.repeat(v, repeats, axis=axis), (_t(x),))


def unbind(input, axis=0):
    return unstack(input, axis)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    """Eager-only (data-dependent output shape): computed on host."""
    xv = np.asarray(_t(x)._value)
    out = np.unique(xv, return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(out, tuple):
        return Tensor(jnp.asarray(out))
    res = [Tensor(jnp.asarray(o)) for o in out]
    # paddle order: (out, index, inverse, counts)
    return tuple(res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    xv = np.asarray(_t(x)._value)
    flat = xv.reshape(-1) if axis is None else xv
    keep = np.ones(flat.shape[0] if axis is None else flat.shape[axis],
                   dtype=bool)
    if axis is None:
        keep[1:] = flat[1:] != flat[:-1]
        out = flat[keep]
    else:
        sl = np.moveaxis(flat, axis, 0)
        keep[1:] = np.any(sl[1:] != sl[:-1],
                          axis=tuple(range(1, sl.ndim)))
        out = np.moveaxis(sl[keep], 0, axis)
    res = [Tensor(jnp.asarray(out))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        res.append(Tensor(jnp.asarray(inv.astype(np.int64))))
    if return_counts:
        idx = np.flatnonzero(keep)
        cnt = np.diff(np.append(idx, keep.shape[0]))
        res.append(Tensor(jnp.asarray(cnt.astype(np.int64))))
    return res[0] if len(res) == 1 else tuple(res)


def masked_select(x, mask, name=None):
    """Eager-only (data-dependent output shape)."""
    xv = np.asarray(_t(x)._value)
    mv = np.asarray(_t(mask)._value)
    return Tensor(jnp.asarray(xv[np.broadcast_to(mv, xv.shape)]))


def masked_fill(x, mask, value, name=None):
    m = _t(mask)._value
    if isinstance(value, Tensor):
        return apply("masked_fill",
                     lambda v, val: jnp.where(m, val.astype(v.dtype), v),
                     (_t(x), value))
    return apply("masked_fill",
                 lambda v: jnp.where(m, jnp.asarray(value, v.dtype), v),
                 (_t(x),))


def masked_scatter(x, mask, value, name=None):
    xv = np.asarray(_t(x)._value)
    mv = np.broadcast_to(np.asarray(_t(mask)._value), xv.shape)
    vv = np.asarray(_t(value)._value).reshape(-1)
    out = xv.copy()
    out[mv] = vv[:mv.sum()]
    return Tensor(jnp.asarray(out))


def where(condition, x=None, y=None, name=None):
    c = _t(condition)
    if x is None and y is None:
        return nonzero(c, as_tuple=True)
    if isinstance(x, Tensor) and isinstance(y, Tensor):
        return apply("where", lambda cc, a, b: jnp.where(cc, a, b), (c, x, y))
    if isinstance(x, Tensor):
        return apply("where", lambda cc, a: jnp.where(cc, a, y), (c, x))
    if isinstance(y, Tensor):
        return apply("where", lambda cc, b: jnp.where(cc, x, b), (c, y))
    return apply("where", lambda cc: jnp.where(cc, x, y), (c,))


def nonzero(x, as_tuple=False):
    """Eager-only (data-dependent output shape)."""
    xv = np.asarray(_t(x)._value)
    nz = np.nonzero(xv)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i.astype(np.int64))) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1).astype(np.int64)))


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    from ..nn import functional as F
    return F.pad(x, pad, mode=mode, value=value, data_format=data_format)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    def fn(v):
        size = index_num // nshards
        shard = v // size
        return jnp.where(shard == shard_id, v % size, ignore_value)
    return apply("shard_index", fn, (_t(input),))


def crop(x, shape=None, offsets=None, name=None):
    s = _shape_list(shape)
    off = [int(o) for o in (offsets or [0] * len(s))]

    def fn(v):
        idx = tuple(_bi.slice(o, o + d if d != -1 else None)
                    for o, d in zip(off, s))
        return v[idx]
    return apply("crop", fn, (_t(x),))


def as_complex(x, name=None):
    return apply("as_complex",
                 lambda v: jax.lax.complex(v[..., 0], v[..., 1]), (_t(x),))


def as_real(x, name=None):
    return apply("as_real",
                 lambda v: jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1),
                 (_t(x),))


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    dt = dtypes.convert_dtype(shape_or_dtype)
    return apply("view_dtype", lambda v: v.view(dt), (_t(x),))


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def as_strided(x, shape, stride, offset=0, name=None):
    def fn(v):
        flat = v.reshape(-1)
        idx = np.zeros(tuple(shape), dtype=np.int64) + offset
        for d, (s, st) in enumerate(zip(shape, stride)):
            ix = np.arange(s) * st
            idx += ix.reshape([-1 if i == d else 1 for i in range(len(shape))])
        return flat[jnp.asarray(idx)]
    return apply("as_strided", fn, (_t(x),))


def atleast_1d(*inputs, name=None):
    outs = [apply("atleast_1d", jnp.atleast_1d, (_t(i),)) for i in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [apply("atleast_2d", jnp.atleast_2d, (_t(i),)) for i in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [apply("atleast_3d", jnp.atleast_3d, (_t(i),)) for i in inputs]
    return outs[0] if len(outs) == 1 else outs


def hsplit(x, num_or_indices, name=None):
    return apply("hsplit", lambda v: tuple(jnp.hsplit(v, num_or_indices)),
                 (_t(x),), multi_output=True)


def vsplit(x, num_or_indices, name=None):
    return apply("vsplit", lambda v: tuple(jnp.vsplit(v, num_or_indices)),
                 (_t(x),), multi_output=True)


def dsplit(x, num_or_indices, name=None):
    return apply("dsplit", lambda v: tuple(jnp.dsplit(v, num_or_indices)),
                 (_t(x),), multi_output=True)


def hstack(x, name=None):
    ts = tuple(_t(i) for i in x)
    return apply("hstack", lambda *vs: jnp.hstack(vs), ts)


def vstack(x, name=None):
    ts = tuple(_t(i) for i in x)
    return apply("vstack", lambda *vs: jnp.vstack(vs), ts)


def dstack(x, name=None):
    ts = tuple(_t(i) for i in x)
    return apply("dstack", lambda *vs: jnp.dstack(vs), ts)


def row_stack(x, name=None):
    return vstack(x)


def column_stack(x, name=None):
    ts = tuple(_t(i) for i in x)
    return apply("column_stack", lambda *vs: jnp.column_stack(vs), ts)


def unflatten(x, axis, shape, name=None):
    s = _shape_list(shape)

    def fn(v):
        ax = axis % v.ndim
        return v.reshape(v.shape[:ax] + tuple(s) + v.shape[ax + 1:])
    return apply("unflatten", fn, (_t(x),))


def unfold(x, axis, size, step, name=None):
    def fn(v):
        n = (v.shape[axis] - size) // step + 1
        starts = jnp.arange(n) * step
        sl = jnp.moveaxis(v, axis, 0)
        win = jax.vmap(
            lambda s: jax.lax.dynamic_slice_in_dim(sl, s, size, 0))(starts)
        # win: (n, size, ...rest) -> (..., n at axis, ..., size last)
        return jnp.moveaxis(jnp.moveaxis(win, 1, -1), 0, axis)
    return apply("unfold", fn, (_t(x),))


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    def fn(a, b):
        diff = a[..., :, None, :] - b[..., None, :, :]
        if p == 2.0:
            return jnp.sqrt(jnp.sum(diff * diff, axis=-1) + 1e-30)
        return jnp.sum(jnp.abs(diff) ** p, axis=-1) ** (1.0 / p)
    return apply("cdist", fn, (_t(x), _t(y)))


def bincount(x, weights=None, minlength=0, name=None):
    xv = _t(x)
    n = int(np.asarray(xv._value).max()) + 1 if xv.size else 0
    length = _bi.max(n, minlength)
    if weights is not None:
        return apply("bincount",
                     lambda v, w: jnp.bincount(v, w, length=length),
                     (xv, _t(weights)))
    return apply("bincount", lambda v: jnp.bincount(v, length=length), (xv,))


def histogram(input, bins=100, min=0, max=0, weight=None, density=False,
              name=None):
    iv = np.asarray(_t(input)._value)
    lo, hi = (min, max) if (min != 0 or max != 0) else (iv.min(), iv.max())
    w = np.asarray(_t(weight)._value) if weight is not None else None
    hist, _ = np.histogram(iv, bins=bins, range=(lo, hi), weights=w,
                           density=density)
    return Tensor(jnp.asarray(hist if density else hist.astype(np.int64)))


def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    xv = np.asarray(_t(x)._value)
    w = np.asarray(_t(weights)._value) if weights is not None else None
    hist, edges = np.histogramdd(xv, bins=bins, range=ranges, density=density,
                                 weights=w)
    return (Tensor(jnp.asarray(hist)),
            [Tensor(jnp.asarray(e)) for e in edges])


# -- round-3 breadth additions (Paddle 3.x surface) --------------------------
def block_diag(inputs, name=None):
    """≙ paddle.block_diag: block-diagonal matrix from a list of 2-D
    tensors [U]."""
    mats = [_t(m) for m in inputs]

    def fn(*ms):
        ms = [jnp.atleast_2d(m) for m in ms]
        rows = sum(m.shape[0] for m in ms)
        cols = sum(m.shape[1] for m in ms)
        out = jnp.zeros((rows, cols), ms[0].dtype)
        r = c = 0
        for m in ms:
            out = out.at[r:r + m.shape[0], c:c + m.shape[1]].set(m)
            r += m.shape[0]
            c += m.shape[1]
        return out
    return apply("block_diag", fn, tuple(mats))


def cartesian_prod(x, name=None):
    """≙ paddle.cartesian_prod: cartesian product of 1-D tensors [U]."""
    ts = [_t(v) for v in x]

    def fn(*vs):
        grids = jnp.meshgrid(*vs, indexing="ij")
        return jnp.stack([g.reshape(-1) for g in grids], axis=-1)
    return apply("cartesian_prod", fn, tuple(ts))


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    """≙ paddle.diagonal_scatter: write y onto the selected diagonal
    of x [U]."""
    def fn(v, s):
        n1, n2 = v.shape[axis1 % v.ndim], v.shape[axis2 % v.ndim]
        if offset >= 0:
            dlen = min(n1, n2 - offset)
            i1 = jnp.arange(dlen)
            i2 = jnp.arange(dlen) + offset
        else:
            dlen = min(n1 + offset, n2)
            i1 = jnp.arange(dlen) - offset
            i2 = jnp.arange(dlen)
        # transpose the two diagonal dims last (matching jnp.diagonal's
        # output layout, which is what `y` must be shaped like), write the
        # diagonal with .at[], untranspose
        perm = [d for d in range(v.ndim)
                if d not in (axis1 % v.ndim, axis2 % v.ndim)] \
            + [axis1 % v.ndim, axis2 % v.ndim]
        inv = [perm.index(d) for d in range(v.ndim)]
        vt = jnp.transpose(v, perm)          # (..., n1, n2)
        vt = vt.at[..., i1, i2].set(s.astype(v.dtype))
        return jnp.transpose(vt, inv)
    return apply("diagonal_scatter", fn, (_t(x), _t(y)))


def select_scatter(x, values, axis, index, name=None):
    """≙ paddle.select_scatter: write `values` into x at `index` along
    `axis` [U]."""
    def fn(v, s):
        idx = [builtins_slice(None)] * v.ndim
        idx[axis % v.ndim] = index
        return v.at[tuple(idx)].set(s.astype(v.dtype))
    return apply("select_scatter", fn, (_t(x), _t(values)))


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    """≙ paddle.slice_scatter [U]."""
    def fn(v, s):
        idx = [builtins_slice(None)] * v.ndim
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            idx[ax % v.ndim] = builtins_slice(st, en, sd)
        return v.at[tuple(idx)].set(s.astype(v.dtype))
    return apply("slice_scatter", fn, (_t(x), _t(value)))


# paddle alias: reverse == flip
reverse = flip
