"""Tensor creation ops. ≙ reference «python/paddle/tensor/creation.py» [U]."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core.tensor import Tensor, apply, to_tensor


def _shape_arg(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.tolist())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s._value) if isinstance(s, Tensor) else int(s)
                 for s in shape)


def _dt(dtype, default=None):
    if dtype is None:
        return default if default is not None else dtypes.get_default_dtype()
    return dtypes.convert_dtype(dtype)


def zeros(shape, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.zeros(_shape_arg(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.ones(_shape_arg(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None) -> Tensor:
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        dtype = dtypes.get_default_dtype() if isinstance(fill_value, float) \
            else None
    v = jnp.full(_shape_arg(shape), fill_value,
                 _dt(dtype) if dtype is not None else None)
    return Tensor(v)


def empty(shape, dtype=None, name=None) -> Tensor:
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None) -> Tensor:
    x = x if isinstance(x, Tensor) else to_tensor(x)
    return Tensor(jnp.zeros(x._value.shape,
                            _dt(dtype, default=x._value.dtype)))


def ones_like(x, dtype=None, name=None) -> Tensor:
    x = x if isinstance(x, Tensor) else to_tensor(x)
    return Tensor(jnp.ones(x._value.shape, _dt(dtype, default=x._value.dtype)))


def full_like(x, fill_value, dtype=None, name=None) -> Tensor:
    x = x if isinstance(x, Tensor) else to_tensor(x)
    return Tensor(jnp.full(x._value.shape, fill_value,
                           _dt(dtype, default=x._value.dtype)))


def empty_like(x, dtype=None, name=None) -> Tensor:
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None) -> Tensor:
    def _scalar(v):
        return v.item() if isinstance(v, Tensor) else v
    start, end, step = _scalar(start), _scalar(end), _scalar(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = (dtypes.get_default_dtype()
                 if any(isinstance(v, float) for v in (start, end, step))
                 else dtypes.int64)
    return Tensor(jnp.arange(start, end, step, _dt(dtype)))


def linspace(start, stop, num, dtype=None, name=None) -> Tensor:
    def _scalar(v):
        return v.item() if isinstance(v, Tensor) else v
    return Tensor(jnp.linspace(_scalar(start), _scalar(stop), int(_scalar(num)),
                               dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.logspace(start, stop, int(num), base=base,
                               dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.eye(int(num_rows),
                          int(num_columns) if num_columns is not None else None,
                          dtype=_dt(dtype)))


def diag(x, offset=0, padding_value=0, name=None) -> Tensor:
    x = x if isinstance(x, Tensor) else to_tensor(x)

    def fn(v):
        out = jnp.diag(v, k=offset)
        if padding_value != 0 and v.ndim == 1:
            mask = jnp.eye(out.shape[0], out.shape[1], k=offset, dtype=bool)
            out = jnp.where(mask, out, jnp.asarray(padding_value, out.dtype))
        return out
    return apply("diag", fn, (x,))


def diagflat(x, offset=0, name=None) -> Tensor:
    x = x if isinstance(x, Tensor) else to_tensor(x)
    return apply("diagflat", lambda v: jnp.diagflat(v, k=offset), (x,))


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None) -> Tensor:
    x = input if isinstance(input, Tensor) else to_tensor(input)

    def fn(v):
        n = v.shape[-1] + abs(offset)
        base = jnp.zeros(v.shape[:-1] + (n, n), v.dtype)
        idx = jnp.arange(v.shape[-1])
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = base.at[..., r, c].set(v)
        if (dim1, dim2) != (-2, -1):
            out = jnp.moveaxis(out, (-2, -1), (dim1, dim2))
        return out
    return apply("diag_embed", fn, (x,))


def meshgrid(*args, **kwargs):
    ts = args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args
    ts = tuple(t if isinstance(t, Tensor) else to_tensor(t) for t in ts)
    return apply("meshgrid", lambda *vs: tuple(jnp.meshgrid(*vs, indexing="ij")),
                 ts, multi_output=True)


def tril(x, diagonal=0, name=None) -> Tensor:
    x = x if isinstance(x, Tensor) else to_tensor(x)
    return apply("tril", lambda v: jnp.tril(v, k=diagonal), (x,))


def triu(x, diagonal=0, name=None) -> Tensor:
    x = x if isinstance(x, Tensor) else to_tensor(x)
    return apply("triu", lambda v: jnp.triu(v, k=diagonal), (x,))


def tril_indices(row, col, offset=0, dtype="int64", name=None) -> Tensor:
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=_dt(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64", name=None) -> Tensor:
    col = col if col is not None else row
    r, c = np.triu_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=_dt(dtype)))


def assign(x, output=None) -> Tensor:
    x = x if isinstance(x, Tensor) else to_tensor(np.asarray(x))
    out = apply("assign", lambda v: v, (x,))
    if output is not None:
        output._assign_inplace(out)
        return output
    return out


def clone(x, name=None) -> Tensor:
    return x.clone()


def complex(real, imag, name=None) -> Tensor:
    return apply("complex", lambda r, i: jax.lax.complex(r, i),
                 (real, imag))


def polar(abs, angle, name=None) -> Tensor:
    return apply("polar",
                 lambda a, th: jax.lax.complex(a * jnp.cos(th),
                                               a * jnp.sin(th)),
                 (abs, angle))


def one_hot(x, num_classes, name=None) -> Tensor:
    x = x if isinstance(x, Tensor) else to_tensor(x)
    return apply("one_hot",
                 lambda v: jax.nn.one_hot(v, num_classes,
                                          dtype=dtypes.get_default_dtype()),
                 (x,))
