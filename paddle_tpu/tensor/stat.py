"""Statistics ops. ≙ reference «python/paddle/tensor/stat.py» [U]."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _axis_arg(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _axis_arg(axis)
    dd = 1 if unbiased else 0
    return apply("var", lambda v: jnp.var(v, axis=ax, ddof=dd,
                                          keepdims=keepdim), (_t(x),))


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _axis_arg(axis)
    dd = 1 if unbiased else 0
    return apply("std", lambda v: jnp.std(v, axis=ax, ddof=dd,
                                          keepdims=keepdim), (_t(x),))


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    ax = _axis_arg(axis)

    def fn(v):
        if mode == "avg":
            return jnp.median(v, axis=ax, keepdims=keepdim)
        # mode='min': lower of the two middle values + its index
        a = v.reshape(-1) if ax is None else jnp.moveaxis(v, ax, -1)
        sv = jnp.sort(a, axis=-1)
        si = jnp.argsort(a, axis=-1)
        k = (a.shape[-1] - 1) // 2
        vals, idx = sv[..., k], si[..., k].astype(jnp.int64)
        if keepdim:
            where = 0 if ax is None else ax
            vals = jnp.expand_dims(vals, where) if ax is not None else \
                vals.reshape((1,) * v.ndim)
            idx = jnp.expand_dims(idx, where) if ax is not None else \
                idx.reshape((1,) * v.ndim)
        return vals, idx
    if mode == "avg":
        return apply("median", fn, (_t(x),))
    return apply("median", fn, (_t(x),), multi_output=True)


def nanmedian(x, axis=None, keepdim=False, mode="avg", name=None):
    ax = _axis_arg(axis)
    return apply("nanmedian",
                 lambda v: jnp.nanmedian(v, axis=ax, keepdims=keepdim),
                 (_t(x),))


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    ax = _axis_arg(axis)
    qs = q.tolist() if isinstance(q, Tensor) else q
    return apply("quantile",
                 lambda v: jnp.quantile(v, jnp.asarray(qs), axis=ax,
                                        keepdims=keepdim,
                                        method=interpolation),
                 (_t(x),))


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear",
                name=None):
    ax = _axis_arg(axis)
    qs = q.tolist() if isinstance(q, Tensor) else q
    return apply("nanquantile",
                 lambda v: jnp.nanquantile(v, jnp.asarray(qs), axis=ax,
                                           keepdims=keepdim,
                                           method=interpolation), (_t(x),))


def corrcoef(x, rowvar=True, name=None):
    return apply("corrcoef",
                 lambda v: jnp.corrcoef(v, rowvar=rowvar), (_t(x),))


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    fw = _t(fweights)._value if fweights is not None else None
    aw = _t(aweights)._value if aweights is not None else None
    return apply("cov",
                 lambda v: jnp.cov(v, rowvar=rowvar,
                                   ddof=1 if ddof else 0,
                                   fweights=fw, aweights=aw), (_t(x),))
