"""Tensor attribute helpers. ≙ reference «python/paddle/tensor/attribute.py» [U]."""
from __future__ import annotations

import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core.tensor import Tensor, to_tensor


def shape(input) -> Tensor:
    t = input if isinstance(input, Tensor) else to_tensor(input)
    return Tensor(jnp.asarray(t.shape, jnp.int64))


def rank(input) -> Tensor:
    t = input if isinstance(input, Tensor) else to_tensor(input)
    return Tensor(jnp.asarray(t.ndim, jnp.int64))


def numel(x, name=None) -> Tensor:
    t = x if isinstance(x, Tensor) else to_tensor(x)
    return Tensor(jnp.asarray(t.size, jnp.int64))


def is_floating_point(x) -> bool:
    return dtypes.is_floating(x.dtype if isinstance(x, Tensor) else x)


def is_integer(x) -> bool:
    return dtypes.is_integer(x.dtype if isinstance(x, Tensor) else x)


def is_complex(x) -> bool:
    return dtypes.is_complex(x.dtype if isinstance(x, Tensor) else x)


def real(x, name=None):
    from .math import real as _r
    return _r(x)


def imag(x, name=None):
    from .math import imag as _i
    return _i(x)
