"""Search/sort ops. ≙ reference «python/paddle/tensor/search.py» [U]."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core.tensor import Tensor, apply, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    dt = dtypes.convert_dtype(dtype)

    def fn(v):
        if axis is None:
            out = jnp.argmax(v.reshape(-1))
            return out.reshape((1,) * v.ndim if keepdim else ()).astype(dt)
        return jnp.argmax(v, axis=int(axis), keepdims=keepdim).astype(dt)
    return apply("argmax", fn, (_t(x),))


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    dt = dtypes.convert_dtype(dtype)

    def fn(v):
        if axis is None:
            out = jnp.argmin(v.reshape(-1))
            return out.reshape((1,) * v.ndim if keepdim else ()).astype(dt)
        return jnp.argmin(v, axis=int(axis), keepdims=keepdim).astype(dt)
    return apply("argmin", fn, (_t(x),))


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    def fn(v):
        out = jnp.argsort(v, axis=axis, stable=stable or descending,
                          descending=descending)
        return out.astype(jnp.int64)
    return apply("argsort", fn, (_t(x),))


def sort(x, axis=-1, descending=False, stable=False, name=None):
    return apply("sort",
                 lambda v: jnp.sort(v, axis=axis, stable=stable or descending,
                                    descending=descending), (_t(x),))


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    if isinstance(k, Tensor):
        k = int(k.item())

    def fn(v):
        ax = v.ndim - 1 if axis is None else axis % v.ndim
        sl = jnp.moveaxis(v, ax, -1)
        if largest:
            vals, idx = jax.lax.top_k(sl, k)
        else:
            vals, idx = jax.lax.top_k(-sl, k)
            vals = -vals
        return (jnp.moveaxis(vals, -1, ax),
                jnp.moveaxis(idx.astype(jnp.int64), -1, ax))
    return apply("topk", fn, (_t(x),), multi_output=True)


def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    side = "right" if right else "left"
    dt = jnp.int32 if out_int32 else jnp.int64

    def fn(seq, v):
        if seq.ndim == 1:
            return jnp.searchsorted(seq, v, side=side).astype(dt)
        flat_seq = seq.reshape(-1, seq.shape[-1])
        flat_v = v.reshape(-1, v.shape[-1])
        out = jax.vmap(lambda s, vv: jnp.searchsorted(s, vv, side=side))(
            flat_seq, flat_v)
        return out.reshape(v.shape).astype(dt)
    return apply("searchsorted", fn, (_t(sorted_sequence), _t(values)))


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def fn(v):
        ax = axis % v.ndim
        sv = jnp.sort(v, axis=ax)
        si = jnp.argsort(v, axis=ax).astype(jnp.int64)
        vals = jnp.take(sv, k - 1, axis=ax)
        idx = jnp.take(si, k - 1, axis=ax)
        if keepdim:
            vals = jnp.expand_dims(vals, ax)
            idx = jnp.expand_dims(idx, ax)
        return vals, idx
    return apply("kthvalue", fn, (_t(x),), multi_output=True)


def mode(x, axis=-1, keepdim=False, name=None):
    def fn(v):
        ax = axis % v.ndim
        sv = jnp.sort(jnp.moveaxis(v, ax, -1), axis=-1)
        n = sv.shape[-1]
        runs = jnp.cumsum(
            jnp.concatenate([jnp.ones(sv.shape[:-1] + (1,), jnp.int32),
                             (sv[..., 1:] != sv[..., :-1]).astype(jnp.int32)],
                            axis=-1), axis=-1)
        counts = jnp.sum(runs[..., :, None] == runs[..., None, :], axis=-1)
        best = jnp.argmax(counts, axis=-1)
        vals = jnp.take_along_axis(sv, best[..., None], axis=-1)[..., 0]
        orig = jnp.moveaxis(v, ax, -1)
        match = orig == vals[..., None]
        idx = (n - 1) - jnp.argmax(jnp.flip(match, -1), axis=-1)
        if keepdim:
            vals, idx = jnp.expand_dims(vals, ax), jnp.expand_dims(idx, ax)
        return vals, idx.astype(jnp.int64)
    out_v, out_i = apply("mode", fn, (_t(x),), multi_output=True)
    return out_v, out_i
