"""Linear algebra ops (`paddle.linalg` namespace). ≙ reference
«python/paddle/tensor/linalg.py» [U]. Heavy decompositions delegate to
jax.numpy.linalg / jax.scipy.linalg (XLA-native)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def norm(x, p=None, axis=None, keepdim=False, name=None):
    def fn(v):
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        if p is None:  # frobenius / 2-norm default
            if ax is None:
                return jnp.sqrt(jnp.sum(v * v))
            return jnp.linalg.norm(v, ord=None, axis=ax, keepdims=keepdim)
        if p == "fro":
            return jnp.linalg.norm(v, ord="fro" if isinstance(ax, tuple)
                                   else None, axis=ax, keepdims=keepdim)
        if p == "nuc":
            return jnp.linalg.norm(v, ord="nuc", axis=ax, keepdims=keepdim)
        if ax is None:
            flat = v.reshape(-1)
            if p == np.inf:
                out = jnp.max(jnp.abs(flat))
            elif p == -np.inf:
                out = jnp.min(jnp.abs(flat))
            elif p == 0:
                out = jnp.sum(flat != 0).astype(v.dtype)
            else:
                out = jnp.sum(jnp.abs(flat) ** p) ** (1.0 / p)
            return out.reshape((1,) * v.ndim) if keepdim else out
        return jnp.linalg.norm(v, ord=p, axis=ax, keepdims=keepdim)
    return apply("norm", fn, (_t(x),))


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply("vector_norm",
                 lambda v: jnp.linalg.vector_norm(v, ord=p, axis=ax,
                                                  keepdims=keepdim), (_t(x),))


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    return apply("matrix_norm",
                 lambda v: jnp.linalg.matrix_norm(v, ord=p, keepdims=keepdim),
                 (_t(x),))


def cond(x, p=None, name=None):
    return apply("cond", lambda v: jnp.linalg.cond(v, p=p), (_t(x),))


def det(x, name=None):
    return apply("det", jnp.linalg.det, (_t(x),))


def slogdet(x, name=None):
    def fn(v):
        sign, logdet = jnp.linalg.slogdet(v)
        return jnp.stack([sign, logdet])
    return apply("slogdet", fn, (_t(x),))


def inv(x, name=None):
    return apply("inv", jnp.linalg.inv, (_t(x),))


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply("pinv",
                 lambda v: jnp.linalg.pinv(v, rtol=rcond, hermitian=hermitian),
                 (_t(x),))


def solve(x, y, name=None):
    return apply("solve", jnp.linalg.solve, (_t(x), _t(y)))


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    return apply("triangular_solve",
                 lambda a, b: jax.scipy.linalg.solve_triangular(
                     a, b, lower=not upper, trans=1 if transpose else 0,
                     unit_diagonal=unitriangular),
                 (_t(x), _t(y)))


def cholesky(x, upper=False, name=None):
    def fn(v):
        l = jnp.linalg.cholesky(v)
        return jnp.swapaxes(l, -1, -2).conj() if upper else l
    return apply("cholesky", fn, (_t(x),))


def cholesky_solve(x, y, upper=False, name=None):
    def fn(b, l):
        return jax.scipy.linalg.cho_solve((l, not upper), b)
    return apply("cholesky_solve", fn, (_t(x), _t(y)))


def lu(x, pivot=True, get_infos=False, name=None):
    def fn(v):
        lu_, piv = jax.scipy.linalg.lu_factor(v)
        return lu_, (piv + 1).astype(jnp.int32)  # paddle pivots are 1-based
    lu_t, piv_t = apply("lu", fn, (_t(x),), multi_output=True)
    if get_infos:
        info = Tensor(jnp.zeros((1,), jnp.int32))
        return lu_t, piv_t, info
    return lu_t, piv_t


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    def fn(lu_, piv):
        n, m = lu_.shape[-2], lu_.shape[-1]
        k = min(n, m)
        l = jnp.tril(lu_[..., :, :k], -1) + jnp.eye(n, k, dtype=lu_.dtype)
        u = jnp.triu(lu_[..., :k, :])
        # permutation matrix from 1-based pivot swaps
        perm = jnp.arange(n)
        def body(i, p):
            j = piv[i] - 1
            pi, pj = p[i], p[j]
            return p.at[i].set(pj).at[j].set(pi)
        perm = jax.lax.fori_loop(0, piv.shape[-1], body, perm)
        pmat = jnp.eye(n, dtype=lu_.dtype)[perm].T
        return pmat, l, u
    return apply("lu_unpack", fn, (_t(x), _t(y)), multi_output=True)


def qr(x, mode="reduced", name=None):
    if mode == "r":
        return apply("qr_r", lambda v: jnp.linalg.qr(v, mode="r"), (_t(x),))
    return apply("qr", lambda v: tuple(jnp.linalg.qr(v, mode=mode)),
                 (_t(x),), multi_output=True)


def svd(x, full_matrices=False, name=None):
    return apply("svd",
                 lambda v: tuple(jnp.linalg.svd(
                     v, full_matrices=full_matrices)),
                 (_t(x),), multi_output=True)


def svdvals(x, name=None):
    return apply("svdvals",
                 lambda v: jnp.linalg.svd(v, compute_uv=False), (_t(x),))


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    def fn(v):
        u, s, vt = jnp.linalg.svd(v, full_matrices=False)
        k = min(q, s.shape[-1])
        return u[..., :k], s[..., :k], jnp.swapaxes(vt, -1, -2)[..., :k]
    return apply("svd_lowrank", fn, (_t(x),), multi_output=True)


def eig(x, name=None):
    """General eigendecomposition — CPU-only in XLA; runs on host."""
    xv = np.asarray(_t(x)._value)
    w, v = np.linalg.eig(xv)
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigvals(x, name=None):
    xv = np.asarray(_t(x)._value)
    return Tensor(jnp.asarray(np.linalg.eigvals(xv)))


def eigh(x, UPLO="L", name=None):
    return apply("eigh",
                 lambda v: tuple(jnp.linalg.eigh(
                     v, symmetrize_input=True)),
                 (_t(x),), multi_output=True)


def eigvalsh(x, UPLO="L", name=None):
    return apply("eigvalsh", lambda v: jnp.linalg.eigvalsh(v), (_t(x),))


def matrix_power(x, n, name=None):
    return apply("matrix_power",
                 lambda v: jnp.linalg.matrix_power(v, n), (_t(x),))


def matrix_rank(x, tol=None, hermitian=False, name=None):
    tv = tol._value if isinstance(tol, Tensor) else tol
    def fn(v):
        s = (jnp.linalg.eigvalsh(v).__abs__() if hermitian
             else jnp.linalg.svd(v, compute_uv=False))
        if tv is None:
            t = s.max(-1, keepdims=True) * max(v.shape[-2:]) * \
                jnp.finfo(s.dtype).eps
        else:
            t = jnp.asarray(tv)
            while t.ndim < s.ndim:
                t = t[..., None]
        return jnp.sum(s > t, axis=-1).astype(jnp.int64)
    return apply("matrix_rank", fn, (_t(x),))


def multi_dot(x, name=None):
    ts = tuple(_t(i) for i in x)
    return apply("multi_dot", lambda *vs: jnp.linalg.multi_dot(vs), ts)


def matrix_exp(x, name=None):
    return apply("matrix_exp", jax.scipy.linalg.expm, (_t(x),))


def lstsq(x, y, rcond=None, driver=None, name=None):
    def fn(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank.astype(jnp.int64), sv
    return apply("lstsq", fn, (_t(x), _t(y)), multi_output=True)


def householder_product(x, tau, name=None):
    def fn(a, t):
        m, n = a.shape[-2], a.shape[-1]
        eye = jnp.eye(m, dtype=a.dtype)
        def body(i, q):
            v = jnp.where(jnp.arange(m) < i, 0.0,
                          jnp.where(jnp.arange(m) == i, 1.0, a[..., :, i]))
            h = eye - t[i] * jnp.outer(v, v)
            return q @ h
        q = jax.lax.fori_loop(0, n, body, eye)
        return q[..., :, :n]
    return apply("householder_product", fn, (_t(x), _t(tau)))


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    def fn(v):
        k = q if q is not None else min(6, *v.shape[-2:])
        a = v - v.mean(axis=-2, keepdims=True) if center else v
        u, s, vt = jnp.linalg.svd(a, full_matrices=False)
        return u[..., :k], s[..., :k], jnp.swapaxes(vt, -1, -2)[..., :k]
    return apply("pca_lowrank", fn, (_t(x),), multi_output=True)


def corrcoef(x, rowvar=True, name=None):
    from .stat import corrcoef as _c
    return _c(x, rowvar)


def bmm(x, y, name=None):
    from .math import bmm as _b
    return _b(x, y)


def dist(x, y, p=2, name=None):
    def fn(a, b):
        d = (a - b).reshape(-1)
        if p == np.inf:
            return jnp.max(jnp.abs(d))
        if p == -np.inf:
            return jnp.min(jnp.abs(d))
        if p == 0:
            return jnp.sum(d != 0).astype(a.dtype)
        return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)
    return apply("dist", fn, (_t(x), _t(y)))


# -- round-3 breadth additions (Paddle 3.x linalg surface) -------------------
def lu_solve(b, lu_data, lu_pivots, trans="N", name=None):
    """≙ paddle.linalg.lu_solve: solve A x = b from lu() factors [U]."""
    tcode = {"N": 0, "T": 1, "H": 2}[trans]

    def fn(bb, lu_, piv):
        return jax.scipy.linalg.lu_solve(
            (lu_, piv.astype(jnp.int32) - 1), bb, trans=tcode)
    return apply("lu_solve", fn, (_t(b), _t(lu_data), _t(lu_pivots)))


def cholesky_inverse(x, upper=False, name=None):
    """≙ paddle.linalg.cholesky_inverse: inverse of A from its Cholesky
    factor [U]."""
    def fn(l):
        eye = jnp.eye(l.shape[-1], dtype=l.dtype)
        return jax.scipy.linalg.cho_solve((l, not upper), eye)
    return apply("cholesky_inverse", fn, (_t(x),))


def matrix_transpose(x, name=None):
    """≙ paddle.linalg.matrix_transpose (swap last two dims) [U]."""
    return apply("matrix_transpose",
                 lambda v: jnp.swapaxes(v, -1, -2), (_t(x),))


def ormqr(x, tau, y, left=True, transpose=False, name=None):
    """≙ paddle.linalg.ormqr: multiply y by Q from a householder-packed
    qr (geqrf-style x, tau) [U]. Built from householder_product — XLA has
    no direct ormqr primitive; Q is materialized (fine for the moderate
    sizes this API sees)."""
    def fn(a, t, b):
        m, k = a.shape[-2], t.shape[-1]
        # pad packed reflectors to (m, m) / tau to (m,) so the product is
        # the FULL orthogonal Q (extra zero-tau reflectors are identity)
        a_full = jnp.zeros(a.shape[:-1] + (m,), a.dtype) \
            .at[..., :, :a.shape[-1]].set(a)
        t_full = jnp.zeros(t.shape[:-1] + (m,), t.dtype) \
            .at[..., :k].set(t)
        q = jax.lax.linalg.householder_product(a_full, t_full)
        qm = jnp.swapaxes(q, -1, -2) if transpose else q
        return qm @ b if left else b @ qm
    return apply("ormqr", fn, (_t(x), _t(tau), _t(y)))


def tensordot(x, y, axes=2, name=None):
    """≙ paddle.tensordot [U]: contract over `axes` — an int (last k of
    x vs first k of y), a single list (same axes both sides), or a pair
    of lists."""
    from ..core.tensor import Tensor

    def _norm_axes(a):
        if isinstance(a, Tensor):
            a = np.asarray(a._value).tolist()
        if isinstance(a, int):
            return a
        a = list(a)
        if len(a) == 2 and isinstance(a[0], (list, tuple, np.ndarray)):
            return ([int(i) for i in a[0]], [int(i) for i in a[1]])
        return ([int(i) for i in a], [int(i) for i in a])

    ax = _norm_axes(axes)
    return apply("tensordot",
                 lambda a, b: jnp.tensordot(a, b, axes=ax),
                 (_t(x), _t(y)))
