"""Math ops. ≙ reference «python/paddle/tensor/math.py» + PHI math kernels
(SURVEY.md §2.1/§2.2 [U]); every op is a pure jnp/lax function executed
through the eager tape (autograd via jax.vjp, no per-op grad code)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core import dtype as dtypes
from ..core.tensor import Tensor, apply, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _unary(op_name, jfn):
    def op(x, name=None):
        return apply(op_name, jfn, (_t(x),))
    op.__name__ = op_name
    op.__qualname__ = op_name
    op.__doc__ = (f"Elementwise {op_name}. "
                  f"TPU-native equivalent of paddle.{op_name}.")
    return op


def _binary(op_name, jfn):
    def op(x, y, name=None):
        xt, yt = isinstance(x, Tensor), isinstance(y, Tensor)
        if xt and yt:
            return apply(op_name, jfn, (x, y))
        if xt:  # y is a python/numpy scalar: keep weak typing (no promotion)
            return apply(op_name, lambda v: jfn(v, y), (x,))
        if yt:
            return apply(op_name, lambda v: jfn(x, v), (y,))
        return apply(op_name, jfn, (_t(x), _t(y)))
    op.__name__ = op_name
    op.__qualname__ = op_name
    op.__doc__ = f"Elementwise {op_name} with broadcasting."
    return op


# -- elementwise unary -------------------------------------------------------
exp = _unary("exp", jnp.exp)
expm1 = _unary("expm1", jnp.expm1)
log = _unary("log", jnp.log)
log2 = _unary("log2", jnp.log2)
log10 = _unary("log10", jnp.log10)
log1p = _unary("log1p", jnp.log1p)
sqrt = _unary("sqrt", jnp.sqrt)
rsqrt = _unary("rsqrt", lax.rsqrt)
square = _unary("square", jnp.square)
abs = _unary("abs", jnp.abs)
sign = _unary("sign", jnp.sign)
neg = _unary("neg", jnp.negative)
negative = neg
reciprocal = _unary("reciprocal", lambda v: 1.0 / v)
sin = _unary("sin", jnp.sin)
cos = _unary("cos", jnp.cos)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
acos = _unary("acos", jnp.arccos)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
cosh = _unary("cosh", jnp.cosh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
acosh = _unary("acosh", jnp.arccosh)
atanh = _unary("atanh", jnp.arctanh)
floor = _unary("floor", jnp.floor)
ceil = _unary("ceil", jnp.ceil)
round = _unary("round", jnp.round)
trunc = _unary("trunc", jnp.trunc)
frac = _unary("frac", lambda v: v - jnp.trunc(v))
erf = _unary("erf", jax.scipy.special.erf)
erfinv = _unary("erfinv", jax.scipy.special.erfinv)
sigmoid = _unary("sigmoid", jax.nn.sigmoid)
def logit(x, eps=None, name=None):
    """≙ paddle.logit: log(x/(1-x)); with eps, x is clamped to
    [eps, 1-eps] first (reference contract)."""
    def fn(v):
        if eps is not None:
            v = jnp.clip(v, eps, 1 - eps)
        return jax.scipy.special.logit(v)
    return apply("logit", fn, (_t(x),))
digamma = _unary("digamma", jax.scipy.special.digamma)
lgamma = _unary("lgamma", jax.scipy.special.gammaln)
angle = _unary("angle", jnp.angle)
conj = _unary("conj", jnp.conj)
real = _unary("real", jnp.real)
imag = _unary("imag", jnp.imag)
deg2rad = _unary("deg2rad", jnp.deg2rad)
rad2deg = _unary("rad2deg", jnp.rad2deg)
exponent = None  # not in reference surface
i0 = _unary("i0", jax.scipy.special.i0)
i1 = _unary("i1", jax.scipy.special.i1)

# -- elementwise binary ------------------------------------------------------
add = _binary("add", jnp.add)
subtract = _binary("subtract", jnp.subtract)
multiply = _binary("multiply", jnp.multiply)
divide = _binary("divide", jnp.divide)
floor_divide = _binary("floor_divide", jnp.floor_divide)
mod = _binary("mod", jnp.mod)
remainder = mod
floor_mod = mod
pow = _binary("pow", jnp.power)
maximum = _binary("maximum", jnp.maximum)
minimum = _binary("minimum", jnp.minimum)
fmax = _binary("fmax", jnp.fmax)
fmin = _binary("fmin", jnp.fmin)
atan2 = _binary("atan2", jnp.arctan2)
hypot = _binary("hypot", jnp.hypot)
logaddexp = _binary("logaddexp", jnp.logaddexp)
heaviside = _binary("heaviside", jnp.heaviside)
gcd = _binary("gcd", jnp.gcd)
lcm = _binary("lcm", jnp.lcm)
ldexp = _binary("ldexp", jnp.ldexp)
copysign = _binary("copysign", jnp.copysign)
nextafter = _binary("nextafter", jnp.nextafter)

# bitwise (on ints/bools)
bitwise_and = _binary("bitwise_and", jnp.bitwise_and)
bitwise_or = _binary("bitwise_or", jnp.bitwise_or)
bitwise_xor = _binary("bitwise_xor", jnp.bitwise_xor)
bitwise_not = _unary("bitwise_not", jnp.bitwise_not)
bitwise_invert = bitwise_not  # paddle 3.x alias
bitwise_left_shift = _binary("bitwise_left_shift", jnp.left_shift)
bitwise_right_shift = _binary("bitwise_right_shift", jnp.right_shift)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    """≙ paddle.scale."""
    s, b = scale, bias
    if bias_after_scale:
        fn = lambda v: v * s + b
    else:
        fn = lambda v: (v + b) * s
    out = apply("scale", fn, (_t(x),))
    if act is not None:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def clip(x, min=None, max=None, name=None):
    lo = min._value if isinstance(min, Tensor) else min
    hi = max._value if isinstance(max, Tensor) else max
    return apply("clip", lambda v: jnp.clip(v, lo, hi), (_t(x),))


def lerp(x, y, weight, name=None):
    if isinstance(weight, Tensor):
        return apply("lerp", lambda a, b, w: a + w * (b - a), (_t(x), _t(y), weight))
    return apply("lerp", lambda a, b: a + weight * (b - a), (_t(x), _t(y)))


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply("addmm",
                 lambda i, a, b: beta * i + alpha * (a @ b),
                 (_t(input), _t(x), _t(y)))


def multiplex(inputs, index, name=None):
    idx = index._value if isinstance(index, Tensor) else jnp.asarray(index)
    return apply("multiplex",
                 lambda *vs: jnp.stack(vs, 0)[idx.reshape(-1),
                                              jnp.arange(vs[0].shape[0])],
                 tuple(_t(i) for i in inputs))


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply("nan_to_num",
                 lambda v: jnp.nan_to_num(v, nan=nan, posinf=posinf,
                                          neginf=neginf), (_t(x),))


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply("stanh", lambda v: scale_b * jnp.tanh(scale_a * v), (_t(x),))


def rsqrt_(x):
    x._assign_inplace(rsqrt(x)); return x


# -- reductions --------------------------------------------------------------
def _axis_arg(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _reduce(op_name, jfn, upcast_int=False):
    def op(x, axis=None, keepdim=False, name=None):
        x = _t(x)
        ax = _axis_arg(axis)

        def fn(v):
            out = jfn(v, axis=ax, keepdims=keepdim)
            if upcast_int and np.issubdtype(v.dtype, np.integer):
                out = out.astype(jnp.int64 if v.dtype == jnp.int64 else jnp.int32)
            return out
        return apply(op_name, fn, (x,))
    op.__name__ = op_name
    return op


sum = _reduce("sum", jnp.sum)
mean = _reduce("mean", jnp.mean)
prod = _reduce("prod", jnp.prod)
max = _reduce("max", jnp.max)
min = _reduce("min", jnp.min)
amax = _reduce("amax", jnp.max)
amin = _reduce("amin", jnp.min)
nansum = _reduce("nansum", jnp.nansum)
nanmean = _reduce("nanmean", jnp.nanmean)
logsumexp_ = None


def logsumexp(x, axis=None, keepdim=False, name=None):
    ax = _axis_arg(axis)
    return apply("logsumexp",
                 lambda v: jax.scipy.special.logsumexp(v, axis=ax,
                                                       keepdims=keepdim),
                 (_t(x),))


def all(x, axis=None, keepdim=False, name=None):
    ax = _axis_arg(axis)
    return apply("all", lambda v: jnp.all(v, axis=ax, keepdims=keepdim), (_t(x),))


def any(x, axis=None, keepdim=False, name=None):
    ax = _axis_arg(axis)
    return apply("any", lambda v: jnp.any(v, axis=ax, keepdims=keepdim), (_t(x),))


def count_nonzero(x, axis=None, keepdim=False, name=None):
    ax = _axis_arg(axis)
    return apply("count_nonzero",
                 lambda v: jnp.count_nonzero(v, axis=ax, keepdims=keepdim)
                 .astype(jnp.int64), (_t(x),))


# -- cumulative --------------------------------------------------------------
def cumsum(x, axis=None, dtype=None, name=None):
    dt = dtypes.convert_dtype(dtype) if dtype is not None else None

    def fn(v):
        if axis is None:
            v = v.reshape(-1)
            return jnp.cumsum(v, dtype=dt)
        return jnp.cumsum(v, axis=int(axis), dtype=dt)
    return apply("cumsum", fn, (_t(x),))


def cumprod(x, dim=None, dtype=None, name=None):
    dt = dtypes.convert_dtype(dtype) if dtype is not None else None
    return apply("cumprod", lambda v: jnp.cumprod(v, axis=int(dim), dtype=dt),
                 (_t(x),))


def cummax(x, axis=None, dtype="int64", name=None):
    ax = 0 if axis is None else int(axis)
    t = _t(x)
    vals = apply("cummax_v", lambda v: lax.associative_scan(
        jnp.maximum, v.reshape(-1) if axis is None else v, axis=ax), (t,))
    idx = apply("cummax_i", lambda v: _running_argextreme(
        v.reshape(-1) if axis is None else v, ax, jnp.greater).astype(
            dtypes.convert_dtype(dtype)), (t,))
    return vals, idx


def cummin(x, axis=None, dtype="int64", name=None):
    ax = 0 if axis is None else int(axis)
    t = _t(x)
    vals = apply("cummin_v", lambda v: lax.associative_scan(
        jnp.minimum, v.reshape(-1) if axis is None else v, axis=ax), (t,))
    idx = apply("cummin_i", lambda v: _running_argextreme(
        v.reshape(-1) if axis is None else v, ax, jnp.less).astype(
            dtypes.convert_dtype(dtype)), (t,))
    return vals, idx


def _running_argextreme(v, axis, cmp):
    n = v.shape[axis]
    idx = jnp.arange(n).reshape([-1 if i == axis % v.ndim else 1
                                 for i in range(v.ndim)])
    idx = jnp.broadcast_to(idx, v.shape)

    def combine(a, b):
        av, ai = a
        bv, bi = b
        take_b = cmp(bv, av)
        return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)
    _, out_idx = lax.associative_scan(combine, (v, idx), axis=axis)
    return out_idx


def logcumsumexp(x, axis=None, name=None):
    def fn(v):
        if axis is None:
            v2 = v.reshape(-1)
            return _logcumsumexp_impl(v2, 0)
        return _logcumsumexp_impl(v, int(axis))
    return apply("logcumsumexp", fn, (_t(x),))


def _logcumsumexp_impl(v, axis):
    def combine(a, b):
        return jnp.logaddexp(a, b)
    return lax.associative_scan(combine, v, axis=axis)


# -- matmul family -----------------------------------------------------------
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    """≙ paddle.matmul → phi::MatmulKernel (SURVEY.md §3.1). Lowers straight
    to XLA dot_general; bf16/fp16 operands hit the MXU natively."""
    def fn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)
    return apply("matmul", fn, (_t(x), _t(y)))


def mm(input, mat2, name=None):
    return apply("mm", jnp.matmul, (_t(input), _t(mat2)))


def bmm(x, y, name=None):
    return apply("bmm", jnp.matmul, (_t(x), _t(y)))


def dot(x, y, name=None):
    return apply("dot", lambda a, b: jnp.sum(a * b, axis=-1), (_t(x), _t(y)))


def inner(x, y, name=None):
    return apply("inner", jnp.inner, (_t(x), _t(y)))


def outer(x, y, name=None):
    return apply("outer", lambda a, b: jnp.outer(a, b), (_t(x), _t(y)))


def mv(x, vec, name=None):
    return apply("mv", jnp.matmul, (_t(x), _t(vec)))


def kron(x, y, name=None):
    return apply("kron", jnp.kron, (_t(x), _t(y)))


def cross(x, y, axis=9, name=None):
    def fn(a, b):
        ax = axis
        if ax == 9:  # paddle default: first axis with dim 3
            ax = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)
    return apply("cross", fn, (_t(x), _t(y)))


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply("trace",
                 lambda v: jnp.trace(v, offset=offset, axis1=axis1, axis2=axis2),
                 (_t(x),))


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply("diagonal",
                 lambda v: jnp.diagonal(v, offset=offset, axis1=axis1,
                                        axis2=axis2), (_t(x),))


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    pre = prepend._value if isinstance(prepend, Tensor) else prepend
    app = append._value if isinstance(append, Tensor) else append
    return apply("diff",
                 lambda v: jnp.diff(v, n=n, axis=axis, prepend=pre, append=app),
                 (_t(x),))


# -- misc --------------------------------------------------------------------
def isfinite(x, name=None):
    return apply("isfinite", jnp.isfinite, (_t(x),))


def isinf(x, name=None):
    return apply("isinf", jnp.isinf, (_t(x),))


def isnan(x, name=None):
    return apply("isnan", jnp.isnan, (_t(x),))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply("isclose",
                 lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol,
                                          equal_nan=equal_nan),
                 (_t(x), _t(y)))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply("allclose",
                 lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol,
                                           equal_nan=equal_nan),
                 (_t(x), _t(y)))


def equal_all(x, y, name=None):
    return apply("equal_all", lambda a, b: jnp.array_equal(a, b),
                 (_t(x), _t(y)))


def increment(x, value=1.0, name=None):
    out = apply("increment", lambda v: v + value, (x,))
    x._assign_inplace(out)
    return x


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def renorm(x, p, axis, max_norm, name=None):
    def fn(v):
        axes = tuple(i for i in range(v.ndim) if i != axis % v.ndim)
        norms = jnp.sum(jnp.abs(v) ** p, axis=axes, keepdims=True) ** (1.0 / p)
        factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        return v * factor
    return apply("renorm", fn, (_t(x),))


def take(x, index, mode="raise", name=None):
    idx = index._value if isinstance(index, Tensor) else jnp.asarray(index)
    m = {"raise": "clip", "clip": "clip", "wrap": "wrap"}[mode]
    return apply("take", lambda v: jnp.take(v.reshape(-1), idx, mode=m), (_t(x),))


def gammaln(x, name=None):
    return lgamma(x)


def polygamma(x, n, name=None):
    return apply("polygamma",
                 lambda v: jax.scipy.special.polygamma(n, v), (_t(x),))


def combinations(x, r=2, with_replacement=False, name=None):
    import itertools
    xv = _t(x)
    n = xv.shape[0]
    gen = (itertools.combinations_with_replacement if with_replacement
           else itertools.combinations)
    idx = np.array(list(gen(range(n), r)), dtype=np.int32).reshape(-1, r)
    return apply("combinations", lambda v: v[idx], (xv,))


def vander(x, n=None, increasing=False, name=None):
    return apply("vander",
                 lambda v: jnp.vander(v, N=n, increasing=increasing), (_t(x),))


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    if x is not None:
        return apply("trapezoid",
                     lambda yy, xx: jnp.trapezoid(yy, xx, axis=axis),
                     (_t(y), _t(x)))
    return apply("trapezoid",
                 lambda yy: jnp.trapezoid(yy, dx=dx if dx is not None else 1.0,
                                          axis=axis), (_t(y),))


def frexp(x, name=None):
    return apply("frexp", lambda v: jnp.frexp(v), (_t(x),), multi_output=True)


def signbit(x, name=None):
    return apply("signbit", jnp.signbit, (_t(x),))


def sgn(x, name=None):
    """≙ paddle.sgn: sign for real, unit-phase for complex [U]."""
    def fn(v):
        if jnp.iscomplexobj(v):
            mag = jnp.abs(v)
            return jnp.where(mag == 0, 0.0 + 0.0j, v / mag)
        return jnp.sign(v)
    return apply("sgn", fn, (_t(x),))


def sinc(x, name=None):
    """≙ paddle.sinc (normalized sinc) [U]."""
    return apply("sinc", jnp.sinc, (_t(x),))


def inverse(x, name=None):
    """≙ paddle.inverse — alias of linalg.inv over batched matrices [U]."""
    return apply("inverse", jnp.linalg.inv, (_t(x),))


def pdist(x, p=2.0, name=None):
    """≙ paddle.pdist: condensed pairwise distances of (N, D) rows [U]."""
    def fn(v):
        n = v.shape[0]
        iu, ju = jnp.triu_indices(n, k=1)
        d = v[iu] - v[ju]
        if p == 2.0:
            return jnp.sqrt(jnp.sum(d * d, axis=-1))
        if p == float("inf"):
            return jnp.max(jnp.abs(d), axis=-1)
        return jnp.sum(jnp.abs(d) ** p, axis=-1) ** (1.0 / p)
    return apply("pdist", fn, (_t(x),))


# -- round-3 breadth additions (Paddle 3.x surface) --------------------------
def float_power(x, y, name=None):
    """≙ paddle.float_power — always computes in float64-compat fp32
    (closest TPU-native: fp32) [U]."""
    def fn(a, b=None):
        a = a.astype(jnp.float32)
        b = (b.astype(jnp.float32) if b is not None
             else jnp.float32(y))
        return a ** b
    if isinstance(y, Tensor):
        return apply("float_power", fn, (_t(x), y))
    return apply("float_power", lambda a: fn(a), (_t(x),))


def positive(x, name=None):
    """≙ paddle.positive (identity on numeric tensors) [U]."""
    return apply("positive", lambda v: +v, (_t(x),))


def isposinf(x, name=None):
    return apply("isposinf", jnp.isposinf, (_t(x),))


def isneginf(x, name=None):
    return apply("isneginf", jnp.isneginf, (_t(x),))


def isreal(x, name=None):
    return apply("isreal", jnp.isreal, (_t(x),))


def gammainc(x, y, name=None):
    """≙ paddle.gammainc — regularized lower incomplete gamma P(x, y)."""
    return apply("gammainc", jax.scipy.special.gammainc, (_t(x), _t(y)))


def gammaincc(x, y, name=None):
    """≙ paddle.gammaincc — regularized upper incomplete gamma Q(x, y)."""
    return apply("gammaincc", jax.scipy.special.gammaincc, (_t(x), _t(y)))


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """≙ paddle.cumulative_trapezoid [U]."""
    def cumtrap(yy, xx=None):
        yl = jax.lax.slice_in_dim(yy, 0, yy.shape[axis] - 1, axis=axis)
        yr = jax.lax.slice_in_dim(yy, 1, yy.shape[axis], axis=axis)
        if xx is not None:
            xl = jax.lax.slice_in_dim(xx, 0, xx.shape[axis] - 1, axis=axis)
            xr = jax.lax.slice_in_dim(xx, 1, xx.shape[axis], axis=axis)
            step = xr - xl
        else:
            step = dx if dx is not None else 1.0
        return jnp.cumsum((yl + yr) * 0.5 * step, axis=axis)
    if x is not None:
        return apply("cumulative_trapezoid",
                     lambda a, b: cumtrap(a, b), (_t(y), _t(x)))
    return apply("cumulative_trapezoid", cumtrap, (_t(y),))


def vecdot(x, y, axis=-1, name=None):
    """≙ paddle.linalg.vecdot / paddle.vecdot [U]."""
    return apply("vecdot",
                 lambda a, b: jnp.vecdot(a, b, axis=axis), (_t(x), _t(y)))


def histogram_bin_edges(input, bins=100, min=0, max=0, name=None):
    """≙ paddle.histogram_bin_edges [U]."""
    lo, hi = float(min), float(max)

    def fn(v):
        l, h = lo, hi
        if l == 0.0 and h == 0.0:
            l, h = jnp.min(v), jnp.max(v)
        return jnp.linspace(l, h, bins + 1, dtype=jnp.float32)
    return apply("histogram_bin_edges", fn, (_t(input),))


def i0e(x, name=None):
    """≙ paddle.i0e [U]: exponentially scaled modified Bessel I0
    (fp32 internally, input dtype preserved)."""
    return apply("i0e", lambda v: jax.scipy.special.i0e(
        v.astype(jnp.float32)).astype(v.dtype), (_t(x),))


def i1e(x, name=None):
    """≙ paddle.i1e [U]: exponentially scaled modified Bessel I1
    (fp32 internally, input dtype preserved)."""
    return apply("i1e", lambda v: jax.scipy.special.i1e(
        v.astype(jnp.float32)).astype(v.dtype), (_t(x),))


def multigammaln(x, p, name=None):
    """≙ paddle.multigammaln [U]: log multivariate gamma (fp32
    internally, input dtype preserved)."""
    return apply("multigammaln",
                 lambda v: jax.scipy.special.multigammaln(
                     v.astype(jnp.float32), int(p)).astype(v.dtype),
                 (_t(x),))


# numpy-style aliases (paddle ships both spellings)
arccos = acos
arcsin = asin
arctan = atan
arccosh = acosh
arcsinh = asinh
arctanh = atanh
