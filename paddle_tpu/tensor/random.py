"""Random ops over a global stateful generator. ≙ reference
«python/paddle/tensor/random.py» + CPU/GPU Generator [U].

JAX PRNG is functional (explicit keys); Paddle's API is stateful. The bridge
is a module-level `Generator` holding a jax PRNG key that is split per call —
deterministic given `paddle_tpu.seed(n)`. NOTE: inside `jax.jit` tracing the
split happens at trace time (randomness frozen into the compiled program);
training-loop randomness (dropout) instead uses the RNG-state tracker in
`paddle_tpu.distributed.fleet.meta_parallel` / `nn.functional.dropout`'s key
plumbing, mirroring the reference's `get_rng_state_tracker` design."""
from __future__ import annotations

import threading

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core.tensor import Tensor, apply, to_tensor


class Generator:
    """Stateful RNG. The key materializes LAZILY: creating it eagerly at
    import time would initialize the XLA backend during `import
    paddle_tpu`, which breaks multi-process entry points that must call
    jax.distributed.initialize first (paddle.distributed.spawn)."""

    def __init__(self, seed: int = 0):
        self._key_ = None
        self._seed = seed
        self._lock = threading.Lock()

    @property
    def _key(self):
        if self._key_ is None:
            self._key_ = jax.random.key(self._seed)
        return self._key_

    @_key.setter
    def _key(self, value):
        self._key_ = value

    def manual_seed(self, seed: int):
        self._key_ = jax.random.key(seed)
        self._seed = seed
        return self

    def initial_seed(self) -> int:
        return self._seed

    def next_key(self):
        with self._lock:
            self._key, sub = jax.random.split(self._key)
        return sub

    def get_state(self):
        return Tensor(jax.random.key_data(self._key))

    def set_state(self, state):
        data = state._value if isinstance(state, Tensor) else jnp.asarray(state)
        self._key = jax.random.wrap_key_data(data)


default_generator = Generator(0)


def seed(s: int) -> Generator:
    """≙ paddle.seed."""
    return default_generator.manual_seed(int(s))


def get_rng_state():
    return [default_generator.get_state()]


def set_rng_state(state_list):
    default_generator.set_state(state_list[0])


def _key():
    return default_generator.next_key()


def _shape_arg(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.tolist())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s._value) if isinstance(s, Tensor) else int(s)
                 for s in shape)


def _dt(dtype):
    return dtypes.convert_dtype(dtype) if dtype is not None \
        else dtypes.get_default_dtype()


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    k = _key() if seed in (0, None) else jax.random.key(seed)
    return Tensor(jax.random.uniform(k, _shape_arg(shape), _dt(dtype),
                                     minval=min, maxval=max))


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype, 0.0, 1.0)


def randn(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(_key(), _shape_arg(shape), _dt(dtype)))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._value if isinstance(mean, Tensor) else mean
        s = std._value if isinstance(std, Tensor) else std
        out_shape = np.broadcast_shapes(np.shape(m), np.shape(s))
        k = _key()
        return Tensor(m + s * jax.random.normal(
            k, out_shape, dtypes.get_default_dtype()))
    sh = _shape_arg(shape if shape is not None else [1])
    return Tensor(mean + std * jax.random.normal(
        _key(), sh, dtypes.get_default_dtype()))


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None):
    k = _key() if seed in (0, None) else jax.random.key(seed)
    return Tensor(mean + std * jax.random.normal(k, _shape_arg(shape),
                                                 _dt(dtype)))


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def standard_gamma(x, name=None):
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.gamma(_key(), xv))


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(_key(), _shape_arg(shape), low, high,
                                     dtypes.convert_dtype(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    if high is None:
        low, high = 0, low
    dt = dtypes.convert_dtype(dtype) if dtype is not None else x.dtype
    return Tensor(jax.random.randint(_key(), tuple(x.shape), low, high, dt))


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(_key(), n).astype(
        dtypes.convert_dtype(dtype)))


def shuffle(x, name=None):
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.permutation(_key(), xv, axis=0))


def multinomial(x, num_samples=1, replacement=False, name=None):
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    logits = jnp.log(jnp.maximum(xv, 1e-30))
    if replacement:
        out = jax.random.categorical(_key(), logits, axis=-1,
                                     shape=xv.shape[:-1] + (num_samples,))
    else:
        k = _key()
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(k, xv.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return Tensor(out.astype(jnp.int64))


def bernoulli(x, name=None):
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.bernoulli(_key(), xv).astype(xv.dtype))


def bernoulli_(x, p=0.5, name=None):
    x._value = jax.random.bernoulli(_key(), p, tuple(x.shape)).astype(
        x._value.dtype)
    return x


def poisson(x, name=None):
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.poisson(_key(), xv).astype(xv.dtype))


def binomial(count, prob, name=None):
    cv = count._value if isinstance(count, Tensor) else jnp.asarray(count)
    pv = prob._value if isinstance(prob, Tensor) else jnp.asarray(prob)
    return Tensor(jax.random.binomial(_key(), cv.astype(jnp.float32),
                                      pv).astype(jnp.int64))


def exponential_(x, lam=1.0, name=None):
    x._value = (jax.random.exponential(_key(), tuple(x.shape)) / lam).astype(
        x._value.dtype)
    return x


def cauchy_(x, loc=0, scale=1, name=None):
    x._value = (loc + scale * jax.random.cauchy(
        _key(), tuple(x.shape))).astype(x._value.dtype)
    return x


def geometric_(x, probs, name=None):
    u = jax.random.uniform(_key(), tuple(x.shape))
    x._value = (jnp.ceil(jnp.log1p(-u) / jnp.log1p(-probs))).astype(
        x._value.dtype)
    return x


def log_normal_(x, mean=1.0, std=2.0, name=None):
    x._value = jnp.exp(mean + std * jax.random.normal(
        _key(), tuple(x.shape))).astype(x._value.dtype)
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    x._value = (mean + std * jax.random.normal(
        _key(), tuple(x.shape))).astype(x._value.dtype)
    return x


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    k = _key() if seed in (0, None) else jax.random.key(seed)
    x._value = jax.random.uniform(k, tuple(x.shape), x._value.dtype,
                                  minval=min, maxval=max)
    return x


def rand_like(x, dtype=None, name=None):
    dt = dtypes.convert_dtype(dtype) if dtype is not None else x._value.dtype
    return Tensor(jax.random.uniform(_key(), tuple(x.shape), dt))


def randn_like(x, dtype=None, name=None):
    dt = dtypes.convert_dtype(dtype) if dtype is not None else x._value.dtype
    return Tensor(jax.random.normal(_key(), tuple(x.shape), dt))


def log_normal(mean=1.0, std=2.0, shape=None, dtype=None, name=None):
    """≙ paddle.log_normal [U]: exp(N(mean, std^2)) samples."""
    shp = _shape_arg(shape) if shape is not None else ()
    out = jnp.exp(mean + std * jax.random.normal(_key(), shp)) \
        .astype(_dt(dtype))
    return Tensor(out)
