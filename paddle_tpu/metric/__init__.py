"""Metrics. ≙ reference «python/paddle/metric/metrics.py» [U]."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor, to_tensor


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, pred, label, *args):
        return pred, label


class Accuracy(Metric):
    """Top-k accuracy. ≙ paddle.metric.Accuracy."""

    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred = np.asarray(pred.numpy() if isinstance(pred, Tensor) else pred)
        label = np.asarray(label.numpy() if isinstance(label, Tensor) else label)
        idx = np.argsort(-pred, axis=-1)[..., :self.maxk]
        if label.ndim == pred.ndim:
            label = label.argmax(-1)
        correct = idx == label[..., None]
        return correct

    def update(self, correct, *args):
        correct = np.asarray(correct.numpy() if isinstance(correct, Tensor)
                             else correct)
        accs = []
        for k in self.topk:
            num = correct[..., :k].any(-1).sum()
            self.total[k] += int(num)
            self.count[k] += int(np.prod(correct.shape[:-1]))
            accs.append(num / max(np.prod(correct.shape[:-1]), 1))
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = {k: 0 for k in self.topk}
        self.count = {k: 0 for k in self.topk}

    def accumulate(self):
        out = [self.total[k] / max(self.count[k], 1) for k in self.topk]
        return out[0] if len(out) == 1 else out

    def name(self):
        return [f"{self._name}_top{k}" for k in self.topk] \
            if len(self.topk) > 1 else [self._name]


class Precision(Metric):
    """Binary precision. ≙ paddle.metric.Precision."""

    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = np.asarray(preds.numpy() if isinstance(preds, Tensor) else preds)
        labels = np.asarray(labels.numpy() if isinstance(labels, Tensor)
                            else labels)
        pred_pos = (preds.reshape(-1) > 0.5)
        lab = labels.reshape(-1).astype(bool)
        self.tp += int((pred_pos & lab).sum())
        self.fp += int((pred_pos & ~lab).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    """Binary recall. ≙ paddle.metric.Recall."""

    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = np.asarray(preds.numpy() if isinstance(preds, Tensor) else preds)
        labels = np.asarray(labels.numpy() if isinstance(labels, Tensor)
                            else labels)
        pred_pos = (preds.reshape(-1) > 0.5)
        lab = labels.reshape(-1).astype(bool)
        self.tp += int((pred_pos & lab).sum())
        self.fn += int((~pred_pos & lab).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC AUC via thresholded confusion bins. ≙ paddle.metric.Auc."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = np.asarray(preds.numpy() if isinstance(preds, Tensor) else preds)
        labels = np.asarray(labels.numpy() if isinstance(labels, Tensor)
                            else labels)
        if preds.ndim == 2:
            preds = preds[:, 1]
        preds = preds.reshape(-1)
        labels = labels.reshape(-1)
        bins = np.minimum((preds * self.num_thresholds).astype(np.int64),
                          self.num_thresholds - 1)
        for b, l in zip(bins, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds, np.int64)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # integrate over thresholds high->low
        pos = self._stat_pos[::-1].cumsum()
        neg = self._stat_neg[::-1].cumsum()
        tpr = pos / tot_pos
        fpr = neg / tot_neg
        return float(np.trapezoid(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional top-k accuracy (paddle.metric.accuracy)."""
    import jax.numpy as jnp
    from ..core.tensor import apply
    lab = label if isinstance(label, Tensor) else to_tensor(label)

    def fn(pred, l):
        idx = jnp.argsort(-pred, axis=-1)[..., :k]
        l2 = l.reshape(l.shape[0], -1)[:, 0]
        ok = jnp.any(idx == l2[:, None], axis=-1)
        return jnp.mean(ok.astype(jnp.float32)).reshape(1)
    return apply("accuracy", fn, (input, lab))
