"""Build script. ≙ reference «setup.py» / «paddle_build.sh» (SURVEY.md §1
L0) collapsed to a thin shim: the heavy lifting (CUDA kernels, codegen,
third-party builds) does not exist here — XLA is prebuilt, the Pallas
kernels are Python, and the one native piece (csrc/native.cc: shared-memory
ring transport + tensor codec) compiles on first import via
paddle_tpu._native (no pybind11; ctypes over a plain .so).

    pip wheel .          # build a wheel
    pip install -e .     # editable install
"""
from setuptools import setup

setup()
