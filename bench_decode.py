#!/usr/bin/env python
"""Serving decode benchmark: KV-cache greedy generation tokens/sec.

≙ the reference inference engine's decode throughput axis (SURVEY.md §1
L10, §7 step 6). Prints ONE JSON line like bench.py (the driver contract
is bench.py; this is the serving-side companion, run ad hoc and recorded
in DECODE_BENCH.json).

The whole generation — prefill + lax.scan decode loop — is one compiled
XLA program (models/generation.py), so the measured number includes no
per-token dispatch. Sync is by D2H fetch (block_until_ready is unreliable
on the axon platform — see bench.py).
"""
import json
import os
import sys
import time
import traceback

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)


def run(on_tpu: bool) -> dict:
    import numpy as np
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1024,
                          intermediate_size=2816, num_hidden_layers=16,
                          num_attention_heads=16, num_key_value_heads=8,
                          max_position_embeddings=2048)
        batch, prompt, new = 8, 512, 256
    else:
        cfg = LlamaConfig.tiny()
        batch, prompt, new = 2, 16, 16

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.to(dtype="bfloat16")
    model.eval()
    ids = paddle.to_tensor(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (batch, prompt)).astype(np.int32))

    # warmup/compile
    toks, _ = model.generate(ids, max_new_tokens=new)
    np.asarray(toks._value)

    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        toks, _ = model.generate(ids, max_new_tokens=new)
    np.asarray(toks._value)
    dt = (time.perf_counter() - t0) / reps

    tps = batch * new / dt

    # continuous-batching throughput: staggered prompt lengths through
    # the slot engine (one compiled decode step; admission in flight).
    # A large prompt_pad bounds the prefill-bucket count, and a full
    # warmup run compiles every program BEFORE the timed pass.
    from paddle_tpu.models.serving import ContinuousBatchingEngine
    rng = np.random.default_rng(1)
    # which attention path the engine runs (ISSUE 6): default ragged,
    # env-switchable so the legacy path stays one knob away in benches
    attention_impl = os.environ.get("PDT_BENCH_ATTENTION_IMPL", "ragged")
    eng = ContinuousBatchingEngine(
        model, max_batch_size=batch,
        max_seq_len=min(cfg.max_position_embeddings, prompt + new),
        prompt_pad=max(prompt // 2, 8),
        attention_impl=attention_impl)
    n_req = batch * 2

    def submit():
        for _ in range(n_req):
            p_len = int(rng.integers(prompt // 2, prompt))
            eng.add_request(
                rng.integers(0, cfg.vocab_size, p_len), new)

    rng = np.random.default_rng(1)
    submit()
    eng.run()                                   # warmup: compiles
    rng = np.random.default_rng(1)
    submit()                                    # identical lengths
    t0 = time.perf_counter()
    results = eng.run()
    cb_dt = time.perf_counter() - t0
    cb_toks = sum(len(v) for v in results.values())
    cb_tps = cb_toks / cb_dt

    return {
        "metric": "llama_decode_tokens_per_sec" if on_tpu
        else "llama_decode_tokens_per_sec_cpu_ci",
        "value": round(tps, 1),
        "unit": "tokens/s",
        "vs_baseline": 0.0,   # no reference decode number exists
        "detail": {
            "device": str(jax.devices()[0].device_kind),
            "batch": batch, "prompt_len": prompt, "new_tokens": new,
            "total_time_s": round(dt, 3),
            "ms_per_token_step": round(dt / new * 1000, 3),
            "continuous_batching_tokens_per_sec": round(cb_tps, 1),
            "continuous_batching_requests": n_req,
            "attention_impl": eng.attn_impl,
        },
    }


def main():
    sys.path.insert(0, REPO)
    import importlib
    bench = importlib.import_module("bench")
    on_tpu = False
    error = None
    if os.environ.get("BENCH_FORCE_CPU"):
        error = "BENCH_FORCE_CPU set"
    else:
        on_tpu = bench.probe_tpu()
        if not on_tpu:
            error = "TPU probe failed; CPU fallback"
    if not on_tpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    try:
        result = run(on_tpu)
    except BaseException:
        result = {"metric": "llama_decode_tokens_per_sec", "value": 0.0,
                  "unit": "tokens/s", "vs_baseline": 0.0,
                  "error": traceback.format_exc(limit=5)[-1200:]}
    if error:
        result["error"] = error
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
