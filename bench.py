#!/usr/bin/env python
"""Benchmark: Llama causal-LM training step on the attached TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is MFU / 0.40 — the BASELINE.json north-star target MFU
(no published reference numbers exist; see BASELINE.md).

Serving-latency detail now carries TTFT/TPOT p50/p95/p99 (the SLO axes,
interpolated from the telemetry histograms via
`observability.slo.quantile_from_buckets`) under
`detail.engine_telemetry` and each `detail.router` fleet run, plus a
`detail.disagg` disaggregated-vs-colocated A/B (TTFT/TPOT p50/p95 per
mode, migration latency histogram, outputs-identical cross-check —
ISSUE 8) whose tokens/sec both gate regressions.

Regression gate: `bench.py --check-regression PREV.json
[--regression-threshold PCT]` runs the bench, emits the JSON line as
usual, then diffs the throughput metrics against the prior BENCH_r*.json
and exits NON-ZERO when any regressed more than PCT % (default 10).
`--current CUR.json` compares two saved results without running
anything (the CI-friendly form).

Model size is chosen to exercise the chip seriously while fitting one
v5e (≈16 GiB HBM) with AdamW fp32 state: ≈255M params, bf16 compute.

Resilience (round-1 postmortem: BENCH_r01 died inside TPU backend init
with no JSON emitted at all): the TPU backend is probed in a SUBPROCESS
with a hard timeout so a hung `jax.devices()` cannot take the bench
down with it; the probe is retried once; on probe failure the bench
falls back to the CPU platform; and every exit path — including an
unexpected exception — prints the JSON line, with an "error" field when
something went wrong, so the driver always captures a parseable result.
"""
import argparse
import json
import os
import subprocess
import sys
import time
import traceback

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

TARGET_MFU = 0.40


def _env_int(name: str, default: str) -> int:
    """PDT_-prefixed knobs win; the unprefixed round-1 names stay as
    fallback so existing driver configs keep working."""
    return int(os.environ.get("PDT_" + name, os.environ.get(name, default)))


# BENCH_r01-r05 postmortem: each run burned 2x240 s on doomed TPU probes
# before the CPU fallback — every probe knob is env-tunable, and
# PDT_BENCH_SKIP_TPU=1 skips probing entirely (straight to CPU).
PROBE_TIMEOUT_S = _env_int("BENCH_TPU_PROBE_TIMEOUT", "240")
PROBE_ATTEMPTS = _env_int("BENCH_TPU_PROBE_ATTEMPTS", "5")
PROBE_BUDGET_S = _env_int("BENCH_TPU_PROBE_BUDGET", "2400")
SKIP_TPU = os.environ.get("PDT_BENCH_SKIP_TPU", "") not in ("", "0")
# ISSUE 6 satellite (BENCH_r01-r05 each burned up to 5x240 s on doomed
# probes before the CPU fallback): the verdict is CACHED in a TTL'd
# file, and after a cached FAILURE the retry ladder drops to
# PROBE_ATTEMPTS_RETRY attempts — a flaky tunnel gets re-checked
# cheaply, not re-besieged.
PROBE_CACHE_PATH = os.environ.get("PDT_BENCH_PROBE_CACHE",
                                  "/tmp/pdt_tpu_probe.json")
PROBE_CACHE_TTL_S = _env_int("BENCH_PROBE_TTL", "3600")
PROBE_ATTEMPTS_RETRY = _env_int("BENCH_PROBE_ATTEMPTS_RETRY", "1")

# which serving attention path the engine benches run (ISSUE 6):
# default ragged; set PDT_BENCH_ATTENTION_IMPL=legacy to A/B
ATTENTION_IMPL = os.environ.get("PDT_BENCH_ATTENTION_IMPL", "ragged")

# what the last probe_tpu() call cost and decided — attached to the
# bench JSON (detail.tpu_probe) so the BENCH_r*.json trajectory shows
# what probing cost each round
PROBE_INFO = {}


def _probe_cache_read():
    """The cached probe verdict, or None when absent/corrupt/expired
    (an expired entry is still returned with "expired": True so a
    re-probe after a failure can shrink its attempt ladder)."""
    try:
        with open(PROBE_CACHE_PATH) as f:
            entry = json.load(f)
        verdict = bool(entry["verdict"])
        age = time.time() - float(entry["ts"])
    except (OSError, ValueError, KeyError, TypeError):
        return None
    if age < 0:                            # clock went backwards
        return None
    return {"verdict": verdict, "age_s": age,
            "expired": age >= PROBE_CACHE_TTL_S}


def _probe_cache_write(verdict: bool, wall_s: float, attempts: int):
    tmp = PROBE_CACHE_PATH + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump({"verdict": verdict, "ts": time.time(),
                       "wall_s": round(wall_s, 3),
                       "attempts": attempts}, f)
        os.replace(tmp, PROBE_CACHE_PATH)
    except OSError:
        pass                               # cache is best-effort


def probe_tpu() -> bool:
    """Check, in a throwaway subprocess, that the TPU backend comes up.

    A hung backend init (observed in round 1: `jax.devices()` blocked
    >120 s inside axon setup) kills only the child; the parent moves on.
    The axon tunnel is known to come and go (round 3: it died mid-session
    and revived hours later), so we retry PROBE_ATTEMPTS times with
    exponential backoff between attempts, bounded by a total wall-clock
    budget PROBE_BUDGET_S.  All three knobs are env-tunable so the driver
    can raise them (PDT_BENCH_TPU_PROBE_ATTEMPTS / _TIMEOUT / _BUDGET;
    unprefixed names accepted as fallback), and PDT_BENCH_SKIP_TPU=1
    bypasses the probe entirely.

    The verdict is cached in PROBE_CACHE_PATH (PDT_BENCH_PROBE_CACHE)
    for PROBE_CACHE_TTL_S seconds. A fresh FAILURE short-circuits the
    probe outright — back-to-back bench/bench_decode runs stop paying
    5x240 s each for the same dead tunnel — and a stale failure caps
    the retry ladder at PROBE_ATTEMPTS_RETRY. A cached SUCCESS is
    never trusted blindly: the tunnel is known to die between runs,
    and skipping the probe would hand the round-1 wedge straight to
    the parent's own backend init — instead it shrinks the ladder to
    one cheap confirming attempt. PROBE_INFO records verdict, wall
    time, attempts, and cache hits for the bench JSON."""
    global PROBE_INFO
    cached = _probe_cache_read()
    if cached is not None and not cached["expired"] \
            and not cached["verdict"]:
        PROBE_INFO = {"verdict": False, "wall_s": 0.0,
                      "attempts": 0, "cached": True,
                      "cache_age_s": round(cached["age_s"], 1)}
        sys.stderr.write(
            f"bench: TPU probe verdict False from cache "
            f"({PROBE_CACHE_PATH}, age {cached['age_s']:.0f}s)\n")
        return False
    attempts_cap = PROBE_ATTEMPTS
    if cached is not None:
        # cached success (fresh or stale) -> one confirming attempt;
        # expired failure -> re-check the tunnel, but cheaply
        attempts_cap = max(1, min(PROBE_ATTEMPTS, PROBE_ATTEMPTS_RETRY))
    code = ("import jax; d = jax.devices(); "
            "assert d and d[0].platform != 'cpu', d; print('ok')")
    t_start = time.monotonic()
    deadline = t_start + PROBE_BUDGET_S
    backoff = 5.0
    verdict = False
    attempts = 0                      # COMPLETED probe subprocesses
    for attempt in range(1, attempts_cap + 1):
        remaining = deadline - time.monotonic()
        if remaining <= 5:
            sys.stderr.write("bench: TPU probe budget exhausted\n")
            break
        try:
            r = subprocess.run(
                [sys.executable, "-c", code], capture_output=True,
                timeout=min(PROBE_TIMEOUT_S, remaining), text=True)
            attempts += 1
            if r.returncode == 0 and "ok" in r.stdout:
                verdict = True
                break
            sys.stderr.write(
                f"bench: TPU probe attempt {attempt} failed "
                f"(rc={r.returncode}): {r.stderr.strip()[-500:]}\n")
        except subprocess.TimeoutExpired:
            attempts += 1
            sys.stderr.write(
                f"bench: TPU probe attempt {attempt} timed out\n")
        if attempt < attempts_cap:
            time.sleep(min(backoff, max(0.0, deadline - time.monotonic())))
            backoff = min(backoff * 2, 120.0)
    wall = time.monotonic() - t_start
    PROBE_INFO = {"verdict": verdict, "wall_s": round(wall, 3),
                  "attempts": attempts, "cached": False}
    _probe_cache_write(verdict, wall, attempts)
    return verdict


def emit(payload: dict) -> None:
    print(json.dumps(payload), flush=True)


def _hist_quantiles(series, qs=(0.5, 0.95, 0.99)):
    """{"p50": ..., "p95": ..., "p99": ...} seconds from a snapshot
    histogram series via the SLO quantile API; None when the series
    never recorded."""
    from paddle_tpu.observability.slo import quantile_from_buckets
    if not series or not series.get("count"):
        return None
    return {f"p{round(q * 100)}":
            round(quantile_from_buckets(series["buckets"], q), 6)
            for q in qs}


def _hist_diff(cur, warm):
    """Subtract a warm-phase snapshot histogram series from the final
    one (count, sum, AND the cumulative buckets), so steady-state
    quantiles/averages exclude compile-heavy warm-up observations.
    Returns a fresh series dict; `cur` may be None/empty."""
    if not cur:
        return cur
    warm = warm or {}
    wb = warm.get("buckets", {})
    return {
        "count": cur["count"] - warm.get("count", 0),
        "sum": cur["sum"] - warm.get("sum", 0.0),
        "buckets": {le: c - wb.get(le, 0)
                    for le, c in cur.get("buckets", {}).items()},
    }


def _compile_delta(snap, warm_snap=None):
    """Per-family `pdt_jit_compiles_total` delta across a timed window
    (ISSUE 20). `warm_snap=None` means the registry was reset at the
    window boundary, so the final counters ARE the delta. Families
    with a zero delta are dropped."""
    cur = snap.get("counters", {}).get("pdt_jit_compiles_total", {})
    warm = (warm_snap or {}).get("counters", {}).get(
        "pdt_jit_compiles_total", {})
    out = {}
    for labels, v in cur.items():
        fam = labels.split('"')[1] if '"' in labels else labels
        d = int(v - warm.get(labels, 0.0))
        if d:
            out[fam] = d
    return out


def _assert_steady_state(where, snap, warm_snap=None):
    """The warm-window contract, finally VERIFIED instead of assumed
    (ISSUE 20): a timed block whose numbers feed REGRESSION_METRICS
    must contain zero jit compiles — one recompile inside the window
    swamps the measurement and grades the wrong thing. A trip means
    the warm phase is too short or a program key is churning
    (the retrace-storm failure mode)."""
    delta = _compile_delta(snap, warm_snap)
    assert not delta, (
        f"{where}: {sum(delta.values())} jit compile(s) inside the "
        f"timed window ({delta}) — warm-up did not reach steady state")


def _profile_detail(snap, warm_snap, gaps=None):
    """`detail.profile`: decode-round decomposition medians over the
    timed window (warm-phase buckets diffed out) + the top-3 dispatch
    gaps from a sampled round, straight off `pdt_profile_*`."""
    comp = {}
    cur = snap.get("histograms", {}).get(
        "pdt_profile_round_seconds", {})
    warm = (warm_snap or {}).get("histograms", {}).get(
        "pdt_profile_round_seconds", {})
    for labels, series in cur.items():
        name = labels.split('"')[1] if '"' in labels else labels
        q = _hist_quantiles(_hist_diff(series, warm.get(labels)),
                            qs=(0.5,))
        if q:
            comp[name] = q["p50"]
    out = {"component_median_s": comp}
    if gaps:
        out["top_gaps"] = [
            {"op_pair": g["op_pair"], "gap_s": round(g["gap_s"], 6)}
            for g in gaps[:3]]
    return out


# dotted paths into the bench JSON that gate regressions (tokens/sec
# family: higher is better)
REGRESSION_METRICS = (
    "detail.tokens_per_sec_per_chip",
    "detail.decode_tokens_per_sec",
    "detail.router.replicas_1_affinity.tokens_per_sec",
    "detail.router.replicas_4_affinity.tokens_per_sec",
    "detail.paged_attention.decode_tokens_per_sec_ragged",
    "detail.paged_attention.mixed_tokens_per_sec_ragged",
    "detail.disagg.colocated.tokens_per_sec",
    "detail.disagg.disaggregated.tokens_per_sec",
    "detail.speculative.spec_decode_tokens_per_sec",
    # soak (ISSUE 11): the open-loop capacity headline — virtual-time
    # deterministic, so the threshold catches real scheduling drift
    "detail.soak.max_sustainable_qps",
    # tensor parallelism (ISSUE 12): the tp=1 row guards the shared
    # engine path; the tp=2 row guards the partitioned dispatch
    # (collective-overhead drift on CPU, the scale story on a chip)
    "detail.tp.tp1.decode_tokens_per_sec",
    "detail.tp.tp2.decode_tokens_per_sec",
    # durability (ISSUE 13): the journaled fleet's decode throughput
    # at the default fsync="terminal" policy — the <=3% overhead bar
    # made a standing regression gate
    "detail.journal.journal_on_decode_tokens_per_sec",
    # gray-failure defense (ISSUE 14): decode throughput with the
    # every-Nth-step numeric sentry attached (the production default;
    # the <=3% overhead bar itself is graded inside detail.sentry)
    "detail.sentry.sentry_on_decode_tokens_per_sec",
    # quantized serving (ISSUE 15): the int8-weights + int8-KV engine's
    # own decode throughput — on the CPU oracle the win is residency
    # (detail.quant.residency_ratio), but this row keeps the quantized
    # dispatch path itself from regressing
    "detail.quant.quant_decode_tokens_per_sec",
    # elastic autoscaling (ISSUE 16): chip-time the autoscaled fleet
    # saved vs a static peak fleet on the same diurnal trace at the
    # same served work — the whole point of elasticity, as a gate
    "detail.autoscale.replica_step_savings_pct",
    # multi-model serving (ISSUE 17): mixed-adapter decode — three
    # hosted models sharing every step's one ragged dispatch via the
    # lora_epilogue row-gather; must beat adapter-serial decode
    # (detail.multimodel.mixed_over_serial_speedup) and not regress
    "detail.multimodel.multimodel_decode_tokens_per_sec",
    # pipelined decode (ISSUE 18): the k=8 deferred-harvest fleet with
    # journal AND sentry attached — group-commit + batched scans must
    # keep the full stack >= 95% of bare-engine (the convergence gate,
    # graded inside detail.async_pipeline), and this row keeps that
    # converged throughput from regressing
    "detail.async_pipeline.async_decode_tokens_per_sec",
)

# latency-family regression gates: LOWER is better, a rise past the
# threshold is the regression (ISSUE 11: the interactive lane's p95
# TTFT under 2x overload must stay guarded like tokens/sec)
REGRESSION_METRICS_LOWER = (
    "detail.soak.overload.interactive_p95_ttft_s",
    # elastic autoscaling (ISSUE 16): the autoscaled fleet's
    # interactive p95 TTFT must track the static peak fleet's, and the
    # hysteresis-bounded burst reaction must not creep
    "detail.autoscale.ttft_p95_autoscaled_s",
    "detail.autoscale.burst_reaction_s",
)


def _dig(d, dotted):
    for part in dotted.split("."):
        if not isinstance(d, dict) or part not in d:
            return None
        d = d[part]
    return d


def check_regression(prev: dict, cur: dict,
                     threshold_pct: float = 10.0):
    """Diff the throughput metrics of two bench results. Returns
    (regressions, compared): human-readable strings for every metric
    that dropped more than `threshold_pct` %, and how many metrics
    were comparable at all (0 = nothing to compare, itself a red
    flag)."""
    regressions, compared = [], 0
    for path, lower_better in \
            [(p, False) for p in REGRESSION_METRICS] \
            + [(p, True) for p in REGRESSION_METRICS_LOWER]:
        p, c = _dig(prev, path), _dig(cur, path)
        if not isinstance(p, (int, float)) or isinstance(p, bool) \
                or not isinstance(c, (int, float)) \
                or isinstance(c, bool) or p <= 0:
            continue
        compared += 1
        if lower_better:
            if c > p * (1.0 + threshold_pct / 100.0):
                regressions.append(
                    f"{path}: {p:g} -> {c:g} "
                    f"({(c / p - 1) * 100:+.1f}%, threshold "
                    f"+{threshold_pct:g}% — lower is better)")
        elif c < p * (1.0 - threshold_pct / 100.0):
            regressions.append(
                f"{path}: {p:g} -> {c:g} ({(c / p - 1) * 100:+.1f}%, "
                f"threshold -{threshold_pct:g}%)")
    return regressions, compared


def bench_decode(model, cfg, on_tpu: bool) -> dict:
    """Steady-state continuous-batching decode throughput on the paged
    engine (VERDICT r4 #1: the decode number must ride bench.py's JSON
    so the driver captures it). Returns a detail sub-dict."""
    import numpy as np
    import paddle_tpu.observability as telemetry
    from paddle_tpu.models.serving import ContinuousBatchingEngine

    model.eval()
    if on_tpu:
        slots, p_len, warm, steps, max_seq = 8, 128, 8, 64, 1024
    else:
        slots, p_len, warm, steps, max_seq = 2, 8, 2, 4, 64
    eng = ContinuousBatchingEngine(model, max_batch_size=slots,
                                   max_seq_len=max_seq,
                                   attention_impl=ATTENTION_IMPL)
    rng = np.random.default_rng(0)
    # engine telemetry rides the same JSON (ISSUE 2): BENCH_r*.json
    # trajectories carry serving signals, not just matmul timings
    telemetry.enable()
    telemetry.reset()
    try:
        for _ in range(slots):
            eng.add_request(list(rng.integers(1, cfg.vocab_size, p_len)),
                            max_new_tokens=max_seq - p_len - 1)
        for _ in range(warm):      # admit + compile prefill/decode
            eng.step()
        warm_snap = telemetry.snapshot()
        t0 = time.perf_counter()
        for _ in range(steps):
            eng.step()
        dt = time.perf_counter() - t0
        snap = telemetry.snapshot()
        # ISSUE 20: the steady-state claim is now checked, not assumed
        _assert_steady_state("bench_decode", snap, warm_snap)
        # dispatch-gap sample of one round (observation only: streams
        # and PRNG state are untouched — see profile_round docstring)
        gaps = eng.profile_round()
    finally:
        telemetry.disable(clear_override=True)
        model.train()
    # every request is admitted during the warm phase, so TTFT here
    # spans the first prefill compile — a COLD-START number, named so
    # it can't be read as steady-state serving latency
    ttft = snap["histograms"].get("pdt_serving_ttft_seconds",
                                  {}).get("", {})
    # steady-state decode only: diff the histogram (count, sum, AND
    # buckets) across the timed window so compile-heavy warm steps
    # skew neither the average nor the quantiles
    dstep = _hist_diff(
        snap["histograms"].get("pdt_serving_decode_step_seconds",
                               {}).get("", {}),
        warm_snap["histograms"].get("pdt_serving_decode_step_seconds",
                                    {}).get("", {}))
    return {
        "decode_tokens_per_sec": round(slots * steps / dt, 1),
        "decode_batch_slots": slots,
        "decode_step_ms": round(dt / steps * 1e3, 3),
        "attention_impl": eng.attn_impl,
        # ISSUE 20: where the decode round's wall actually goes (the
        # fusion ladder's shopping list rides the bench JSON)
        "profile": _profile_detail(snap, warm_snap, gaps),
        "engine_telemetry": {
            "ttft_cold_avg_s": round(ttft["sum"] / ttft["count"], 4)
            if ttft.get("count") else None,
            # SLO axes (interpolated from the le buckets; TTFT here is
            # cold-start — see the comment above)
            "ttft_quantiles_s": _hist_quantiles(ttft),
            "tpot_quantiles_s": _hist_quantiles(
                snap["histograms"].get("pdt_serving_tpot_seconds",
                                       {}).get("")),
            # steady-state: the warm-phase buckets are diffed out
            "decode_step_quantiles_s": _hist_quantiles(dstep),
            "decode_step_avg_ms": round(
                1e3 * dstep["sum"] / dstep["count"], 3)
            if dstep.get("count") else None,
            "decode_tokens_per_sec_last_step": round(telemetry.value(
                "pdt_serving_tokens_per_sec"), 1),
            "decode_tokens_total": int(telemetry.value(
                "pdt_serving_decode_tokens_total")),
            "preemptions": int(telemetry.value(
                "pdt_serving_preemptions_total")),
            "page_occupancy": round(telemetry.value(
                "pdt_serving_page_occupancy"), 4),
        },
    }


def bench_router(model, cfg, on_tpu: bool) -> dict:
    """Fleet-layer proxy numbers (ISSUE 4): aggregate tokens/sec for a
    1- vs 4-replica fleet and the prefix-affinity hit rate, plus the
    affinity-vs-round-robin prefix-cache comparison on a deterministic
    shared-prefix workload. Replicas here are engine objects stepped in
    one process — a CPU-mesh proxy for placement QUALITY (cache hits),
    not a parallel-speedup measurement. Returns a detail sub-dict."""
    import numpy as np
    import paddle_tpu.observability as telemetry
    from paddle_tpu.models.serving import ContinuousBatchingEngine
    from paddle_tpu.serving import ServingRouter

    model.eval()
    page = 16
    if on_tpu:
        groups, per_group, sys_pages, new_toks, slots = 8, 8, 8, 32, 4
    else:
        groups, per_group, sys_pages, new_toks, slots = 3, 4, 2, 6, 2
    # slots < per_group so a group's later requests land AFTER its
    # first prefill registered the shared pages — prefix hits need
    # temporal locality, which a same-batch admission can't have
    rng = np.random.default_rng(0)
    # G system prompts, each shared by K requests with distinct tails —
    # the workload prefix-affinity exists for
    prompts = []
    for g in range(groups):
        system = rng.integers(1, cfg.vocab_size, sys_pages * page).tolist()
        for _ in range(per_group):
            prompts.append(system + rng.integers(
                1, cfg.vocab_size, int(rng.integers(3, 7))).tolist())

    def fleet_run(n, policy):
        telemetry.enable()
        telemetry.reset()
        try:
            router = ServingRouter(
                lambda i: ContinuousBatchingEngine(
                    model, max_batch_size=slots, page_size=page,
                    max_seq_len=sys_pages * page + 64,
                    enable_prefix_caching=True,
                    attention_impl=ATTENTION_IMPL),
                num_replicas=n, policy=policy, page_size=page)
            for p in prompts:
                router.submit(p, max_new_tokens=new_toks)
            t0 = time.perf_counter()
            out = router.run()
            dt = time.perf_counter() - t0
            info = router.fleet_info()
            admissions = telemetry.value("pdt_serving_admissions_total")
            aff = telemetry.value("pdt_router_affinity_hit_rate") \
                if policy == "prefix_affinity" else None
            hists = telemetry.snapshot()["histograms"]
        finally:
            telemetry.disable(clear_override=True)
        toks = sum(len(v) for v in out.values())
        return {
            "tokens_per_sec": round(toks / dt, 1),
            "prefix_hit_rate": round(info["prefix_hits"]
                                     / max(1, admissions), 4),
            "prefix_tokens_reused": int(info["prefix_tokens_reused"]),
            "affinity_hit_rate": aff if aff is None else round(aff, 4),
            # fleet-wide SLO axes for this run (all replicas aggregate
            # into the same process-global histograms)
            "ttft_quantiles_s": _hist_quantiles(
                hists.get("pdt_serving_ttft_seconds", {}).get("")),
            "tpot_quantiles_s": _hist_quantiles(
                hists.get("pdt_serving_tpot_seconds", {}).get("")),
        }

    try:
        one = fleet_run(1, "prefix_affinity")
        four = fleet_run(4, "prefix_affinity")
        four_rr = fleet_run(4, "round_robin")
        return {"router": {
            "replicas_1_affinity": one,
            "replicas_4_affinity": four,
            "replicas_4_round_robin": four_rr,
            "affinity_vs_round_robin_prefix_reuse": round(
                four["prefix_tokens_reused"]
                / max(1, four_rr["prefix_tokens_reused"]), 3),
        }}
    finally:
        model.train()


def bench_disagg(model, cfg, on_tpu: bool) -> dict:
    """Disaggregated-vs-colocated A/B (ISSUE 8): the SAME shared-prefix
    workload through a colocated fleet and a prefill:N,decode:N fleet —
    TTFT and TPOT p50/p95 per mode, aggregate tokens/sec (both gated by
    --check-regression), the migration latency histogram
    (pdt_transfer_seconds), and an outputs-identical cross-check of the
    acceptance property. CPU-mesh proxy numbers like bench_router:
    replicas are engines stepped in one process, so the A/B measures
    scheduling + transfer overhead, not parallel speedup."""
    import numpy as np
    import paddle_tpu.observability as telemetry
    from paddle_tpu.models.serving import ContinuousBatchingEngine
    from paddle_tpu.serving import ServingRouter

    model.eval()
    page = 16
    if on_tpu:
        groups, per_group, sys_pages, new_toks, slots = 6, 6, 8, 32, 4
        roles = "prefill:2,decode:2"
    else:
        groups, per_group, sys_pages, new_toks, slots = 2, 4, 2, 6, 2
        roles = "prefill:1,decode:1"
    n_replicas = sum(int(p.split(":")[1]) for p in roles.split(","))
    rng = np.random.default_rng(0)
    prompts = []
    for g in range(groups):
        system = rng.integers(1, cfg.vocab_size, sys_pages * page).tolist()
        for _ in range(per_group):
            prompts.append(system + rng.integers(
                1, cfg.vocab_size, int(rng.integers(3, 7))).tolist())

    def fleet_run(mode_roles):
        telemetry.enable()
        telemetry.reset()
        try:
            router = ServingRouter(
                lambda i: ContinuousBatchingEngine(
                    model, max_batch_size=slots, page_size=page,
                    max_seq_len=sys_pages * page + 64,
                    enable_prefix_caching=True,
                    attention_impl=ATTENTION_IMPL),
                num_replicas=n_replicas, policy="prefix_affinity",
                page_size=page, roles=mode_roles)
            ids = [router.submit(p, max_new_tokens=new_toks)
                   for p in prompts]
            t0 = time.perf_counter()
            out = router.run()
            dt = time.perf_counter() - t0
            info = router.fleet_info()
            hists = telemetry.snapshot()["histograms"]
        finally:
            telemetry.disable(clear_override=True)
        toks = sum(len(v) for v in out.values())
        stats = {
            "tokens_per_sec": round(toks / dt, 1),
            "ttft_quantiles_s": _hist_quantiles(
                hists.get("pdt_serving_ttft_seconds", {}).get(""),
                qs=(0.5, 0.95)),
            "tpot_quantiles_s": _hist_quantiles(
                hists.get("pdt_serving_tpot_seconds", {}).get(""),
                qs=(0.5, 0.95)),
            "migrations": info.get("migrations", 0),
            "prefix_tokens_reused": int(info["prefix_tokens_reused"]),
        }
        if mode_roles is not None:
            stats["migration_latency_s"] = _hist_quantiles(
                hists.get("pdt_transfer_seconds", {}).get(""),
                qs=(0.5, 0.95))
            stats["prefix_store"] = info.get("prefix_store")
        return stats, [out[i] for i in ids]

    try:
        colo, out_c = fleet_run(None)
        disagg, out_d = fleet_run(roles)
        return {"disagg": {
            "roles": roles,
            "colocated": colo,
            "disaggregated": disagg,
            # the acceptance property, re-proved on the bench workload
            "outputs_identical": out_c == out_d,
        }}
    finally:
        model.train()


def bench_speculative(model, cfg, on_tpu: bool) -> dict:
    """Speculative-decoding A/B (ISSUE 10): the SAME shared-prefix
    workload through a plain engine and SELF-DRAFT (target==draft,
    acceptance ≈ 1) speculative engines at k ∈ {2, 4, 8}. Self-draft
    isolates the MECHANISM's win — k draft steps fused into one scan
    dispatch + one batched verify replace k+1 per-token decode
    dispatches — from draft-model quality; a real deployment's
    smaller draft only widens the gap. Reports effective tokens/sec
    (full run, admission included, measured identically across
    configs), acceptance rate, and the draft pass's share of decode
    wall time; `spec_decode_tokens_per_sec` (the k=4 run) gates
    regressions."""
    import numpy as np
    import paddle_tpu.observability as telemetry
    from paddle_tpu.models.serving import (ContinuousBatchingEngine,
                                           SpecConfig)

    model.eval()
    if on_tpu:
        slots, jobs, sys_len, tail, new_toks = 8, 16, 64, 6, 64
    else:
        slots, jobs, sys_len, tail, new_toks = 2, 4, 8, 4, 24
    rng = np.random.default_rng(0)
    system = rng.integers(1, cfg.vocab_size, sys_len).tolist()
    prompts = [system + rng.integers(1, cfg.vocab_size, tail).tolist()
               for _ in range(jobs)]
    max_seq = sys_len + tail + new_toks + 16

    def engine_run(spec):
        eng = ContinuousBatchingEngine(
            model, max_batch_size=slots, max_seq_len=max_seq,
            spec_decode=spec)

        def one_pass():
            for p in prompts:
                eng.add_request(p, max_new_tokens=new_toks)
            t0 = time.perf_counter()
            out = eng.run()
            return (sum(len(v) for v in out.values()),
                    time.perf_counter() - t0)

        telemetry.enable()
        telemetry.reset()
        try:
            # TWO warm-up passes: slot-finish desync in later passes
            # reaches admission/verify shapes the all-fresh first pass
            # never minted, and a compile inside a timed pass would
            # swamp the measurement. Then best-of-3 timed passes (the
            # `_time` discipline elsewhere in this file) so a
            # scheduler hiccup cannot flip the A/B verdict.
            one_pass()
            one_pass()
            telemetry.reset()
            best = (0, 1.0)
            for _ in range(3):
                toks, dt = one_pass()
                if toks / dt > best[0] / best[1]:
                    best = (toks, dt)
            toks, dt = best
            snap = telemetry.snapshot()
            # ISSUE 20: the two warm passes must have minted every
            # admission/verify shape — a compile inside a timed pass
            # is exactly what would swamp the A/B
            _assert_steady_state(
                "bench_speculative"
                + ("[plain]" if spec is None else f"[k{spec.k}]"),
                snap)
            hists = snap["histograms"]
        finally:
            telemetry.disable(clear_override=True)
        stats = {"tokens_per_sec": round(toks / dt, 1)}
        if spec is not None:
            info = eng.spec_info()
            draft_s = hists.get("pdt_spec_draft_seconds",
                                {}).get("", {})
            step_s = hists.get("pdt_serving_decode_step_seconds",
                               {}).get("", {})
            stats["acceptance_rate"] = round(info["acceptance_rate"], 4)
            stats["rounds"] = info["rounds"]
            if step_s.get("count"):
                stats["draft_overhead_frac"] = round(
                    draft_s.get("sum", 0.0)
                    / max(step_s.get("sum", 0.0), 1e-9), 4)
        return stats

    try:
        out = {"plain": engine_run(None)}
        for k in (2, 4, 8):
            out[f"k{k}"] = engine_run(SpecConfig(model, k=k))
        out["spec_decode_tokens_per_sec"] = \
            out["k4"]["tokens_per_sec"]
        out["speedup_vs_plain_at_k4"] = round(
            out["k4"]["tokens_per_sec"]
            / max(out["plain"]["tokens_per_sec"], 1e-9), 3)
        return {"speculative": out}
    finally:
        model.train()


def bench_tp(on_tpu: bool) -> dict:
    """Tensor-parallel serving A/B (ISSUE 12, serving/submesh.py):
    the SAME workload through tp=1 / tp=2 / tp=4 engines — decode
    tokens/sec, prefill (admission) wall, an outputs-identical
    cross-check against tp=1 (the exact-mode guarantee), and one
    tp=2 -> tp=2 migration's per-shard payload bytes. On the
    8-simulated-device CPU mesh the tp>1 rows measure partitioning
    OVERHEAD (host collectives cost more than tiny-model math saves);
    on a real chip the same rows become the scale story. The bench
    model uses 8 q / 4 kv heads so tp=4 still shards the pages."""
    import numpy as np
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.observability as telemetry
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.serving import ContinuousBatchingEngine
    from paddle_tpu.serving import TpConfig, carve_submeshes, transfer

    cfg = LlamaConfig(vocab_size=512, hidden_size=128,
                      intermediate_size=256, num_hidden_layers=2,
                      num_attention_heads=8, num_key_value_heads=4,
                      max_position_embeddings=256)
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    new_toks = 32 if on_tpu else 12
    n_jobs = 8 if on_tpu else 6
    rng = np.random.default_rng(0)
    jobs = [rng.integers(1, cfg.vocab_size,
                         int(rng.integers(8, 24))).tolist()
            for _ in range(n_jobs)]
    n_dev = len(jax.devices())

    def engine(sm):
        # batch covers every job so the ONE timed eng.step() admits the
        # whole workload — decode_dt then measures decode dispatches
        # only (queued jobs would otherwise prefill inside the timed
        # decode window and pollute the gated decode_tokens_per_sec)
        return ContinuousBatchingEngine(
            model, max_batch_size=n_jobs, max_seq_len=128, submesh=sm,
            attention_impl="ragged")

    def timed_run(sm):
        # ONE engine across both phases: the warm pass compiles every
        # program (jit caches are per-engine), the timed pass then
        # measures steady-state admission + decode walls
        eng = engine(sm)
        telemetry.enable()
        telemetry.reset()
        try:
            warm_snap = None
            for phase in ("warm", "timed"):
                if phase == "timed":
                    warm_snap = telemetry.snapshot()
                rids = [eng.add_request(p, new_toks) for p in jobs]
                t0 = time.perf_counter()
                eng.step()                   # the admission dispatch
                prefill_dt = time.perf_counter() - t0
                t1 = time.perf_counter()
                out = eng.run()
                decode_dt = time.perf_counter() - t1
            # ISSUE 20: the warm pass really did compile every program
            _assert_steady_state(
                f"bench_tp[tp{1 if sm is None else getattr(sm, 'tp', '?')}]",
                telemetry.snapshot(), warm_snap)
        finally:
            telemetry.disable(clear_override=True)
        toks = sum(len(out[r]) for r in rids)
        return {
            "decode_tokens_per_sec": round(
                (toks - n_jobs) / max(decode_dt, 1e-9), 1),
            "prefill_wall_s": round(prefill_dt, 4),
            "total_tokens": toks,
        }, [out[r] for r in rids]

    result = {}
    base, want = timed_run(None)
    result["tp1"] = base
    for tp in (2, 4):
        if tp > n_dev:
            # visible skip marker — a missing tp2 row would silently
            # drop detail.tp.tp2.* out of the regression gate
            result[f"tp{tp}"] = {
                "skipped": f"needs {tp} devices, have {n_dev}"}
            continue
        sm = carve_submeshes(1, TpConfig(tp=tp))[0]
        row, got = timed_run(sm)
        row["outputs_identical_to_tp1"] = got == want
        result[f"tp{tp}"] = row

    # per-shard migration payload: one tp=2 -> tp=2 move
    if n_dev >= 4:
        telemetry.enable()
        telemetry.reset()
        try:
            sms = carve_submeshes(2, TpConfig(tp=2))
            src, dst = engine(sms[0]), engine(sms[1])
            rid = src.add_request(jobs[0], new_toks)
            for _ in range(3):
                src.step()
            t0 = time.perf_counter()
            req, payload = transfer.migrate_request(src, dst, rid)
            mig_dt = time.perf_counter() - t0
            shard_bytes = {
                s: int(telemetry.value(
                    "pdt_tp_migration_shard_bytes_total", shard=s))
                for s in ("0", "1")}
            result["migration"] = {
                "payload_nbytes": transfer.payload_nbytes(payload),
                "per_shard_bytes": shard_bytes,
                "wall_s": round(mig_dt, 4),
            }
        finally:
            telemetry.disable(clear_override=True)
    return {"tp": result}


def bench_soak(model, cfg, on_tpu: bool) -> dict:
    """Open-loop soak capacity (ISSUE 11): max-sustainable-QPS by
    binary search over the arrival rate of a seeded trace driven
    through a 2-replica fleet in VIRTUAL time, then a 2x-overload run
    with the QoS admission controller on. Virtual-time determinism
    makes both headline numbers exact replay quantities, so the
    regression gate catches scheduling drift, not timer noise.
    Returns a detail sub-dict (`detail.soak`)."""
    import paddle_tpu.observability as telemetry
    from paddle_tpu.loadgen import (SoakDriver, TraceConfig,
                                    VirtualClock, binary_search_qps,
                                    generate_trace)
    from paddle_tpu.models.serving import ContinuousBatchingEngine
    from paddle_tpu.observability.slo import SloMonitor, SloObjective
    from paddle_tpu.serving import QosAdmission, ServingRouter

    page = 16
    step_dt = 0.05
    objective_s = 0.5              # interactive p95 TTFT bound
    if on_tpu:
        slots, duration, out_max, prompt_max = 8, 30.0, 24, 64
    else:
        slots, duration, out_max, prompt_max = 2, 12.0, 10, 24

    def soak(qps, with_qos):
        clock = VirtualClock()
        mon = qos = None
        if with_qos:
            mon = SloMonitor(
                [SloObjective("interactive_ttft_p95",
                              "ttft.interactive", "latency",
                              objective_s, quantile=0.95,
                              window_s=duration)],
                clock=clock)
            qos = QosAdmission(slo_monitor=mon,
                               shed_objective="interactive_ttft_p95",
                               shed_burn=0.5, clock=clock)
        router = ServingRouter(
            lambda i: ContinuousBatchingEngine(
                model, max_batch_size=slots, page_size=page,
                max_seq_len=prompt_max + out_max + 2 * page,
                attention_impl=ATTENTION_IMPL, clock=clock),
            num_replicas=2, policy="least_outstanding", page_size=page,
            max_replica_outstanding=4 * slots, clock=clock,
            sleep=clock.advance, slo_monitor=mon, admission=qos)
        trace = generate_trace(TraceConfig(
            seed=0, duration_s=duration, base_qps=qps,
            diurnal_amplitude=0.2, diurnal_period_s=duration,
            burst_start_prob=0.02, burst_mean_s=1.0,
            burst_multiplier=2.0,
            prompt_len_median=8.0, prompt_len_max=prompt_max,
            output_len_median=6.0, output_len_max=out_max,
            # the 2x-overload phase must be winnable for QoS:
            # interactive_share x 2 < 1 (docs/serving.md)
            interactive_fraction=0.4,
            vocab_size=cfg.vocab_size))
        return SoakDriver(router, trace, clock=clock, step_dt=step_dt,
                          max_wall_s=240).run().summary()

    probes = {}                    # qps -> summary (soaks replay
    #                                deterministically: probe once)

    def sustainable(qps):
        if qps not in probes:
            probes[qps] = soak(qps, with_qos=False)
        s = probes[qps]
        inter = s["lanes"].get("interactive", {})
        p95 = inter.get("ttft_p95_s")
        # sustainable = nothing refused AND nothing admitted-then-lost
        # (preempted/timeout sessions produce no TTFT sample, so the
        # p95 alone would grade a lossy rate as fine)
        served_all = s["outcomes"].get("finished", 0) == s["sessions"]
        return served_all and (p95 is None or p95 <= objective_s)

    telemetry.enable()
    telemetry.reset()
    try:
        model.eval()
        max_qps = binary_search_qps(sustainable, 0.5, 4.0, iters=5)
        at_max = probes.get(max_qps) or soak(max_qps, with_qos=False)
        over = soak(max_qps * 2.0, with_qos=True)
    finally:
        model.train()
        telemetry.disable(clear_override=True)
    inter_over = over["lanes"].get("interactive", {})
    batch_over = over["lanes"].get("batch", {})
    return {"soak": {
        "step_dt_s": step_dt,
        "ttft_objective_s": objective_s,
        "max_sustainable_qps": round(max_qps, 3),
        "interactive_p95_ttft_s": (at_max["lanes"]
                                   .get("interactive", {})
                                   .get("ttft_p95_s")),
        "overload": {
            "arrival_qps": over["arrival_qps"],
            "interactive_p95_ttft_s": inter_over.get("ttft_p95_s"),
            "interactive_shed": inter_over.get("shed", 0),
            "batch_shed": batch_over.get("shed", 0),
            "outcomes": over["outcomes"],
            "sheds_by_reason": over["sheds_by_reason"],
        },
    }}


def bench_autoscale(model, cfg, on_tpu: bool) -> dict:
    """Elastic autoscaling (ISSUE 16): one pronounced-diurnal trace
    driven twice in virtual time — a STATIC fleet pinned at peak size,
    then an AUTOSCALED one (journal-attached: every resize a two-phase
    INTENT/COMMIT transaction) starting at one replica under a
    `FleetAutoscaler` with the arrival-rate capacity model. The
    headline is `replica_step_savings_pct` — chip-time the elastic
    fleet did NOT spend for the same served work — gated higher-better
    in REGRESSION_METRICS, with the autoscaled interactive p95 TTFT
    and the burst reaction time gated lower-better. Virtual-time
    determinism makes all three exact replay quantities. Returns a
    detail sub-dict (`detail.autoscale`)."""
    import os
    import shutil
    import tempfile

    import paddle_tpu.observability as telemetry
    from paddle_tpu.loadgen import (SoakDriver, TraceConfig,
                                    VirtualClock, generate_trace)
    from paddle_tpu.models.serving import ContinuousBatchingEngine
    from paddle_tpu.serving import (AutoscalePolicy, FleetAutoscaler,
                                    RouterJournal, ServingRouter)

    page = 16
    step_dt = 0.05
    peak_replicas = 2
    if on_tpu:
        slots, duration, out_max, prompt_max = 8, 80.0, 24, 64
        replica_qps, base_qps = 4.0, 4.8
    else:
        slots, duration, out_max, prompt_max = 2, 40.0, 10, 24
        # one replica's capacity share + a base whose diurnal peak
        # (1.6x) needs the whole fleet and whose trough (0.4x) fits
        # one replica — the gap elasticity harvests
        replica_qps, base_qps = 1.0, 1.2

    def trace():
        return generate_trace(TraceConfig(
            seed=1, duration_s=duration, base_qps=base_qps,
            diurnal_amplitude=0.6, diurnal_period_s=duration,
            burst_start_prob=0.0, burst_mean_s=1.0,
            burst_multiplier=1.0,
            prompt_len_median=8.0, prompt_len_max=prompt_max,
            output_len_median=6.0, output_len_max=out_max,
            interactive_fraction=0.4,
            vocab_size=cfg.vocab_size))

    def drive(autoscaled, journal=None):
        clock = VirtualClock()
        router = ServingRouter(
            lambda i: ContinuousBatchingEngine(
                model, max_batch_size=slots, page_size=page,
                max_seq_len=prompt_max + out_max + 2 * page,
                attention_impl=ATTENTION_IMPL, clock=clock),
            num_replicas=peak_replicas, policy="least_outstanding",
            page_size=page, max_replica_outstanding=4 * slots,
            clock=clock, sleep=clock.advance, journal=journal)
        scaler = None
        if autoscaled:
            router.resize(num_replicas=1,
                          reason="autoscale-bench-floor")
            scaler = FleetAutoscaler(
                router,
                AutoscalePolicy(
                    min_replicas=1, max_replicas=peak_replicas,
                    scale_up_depth=2.0 * slots, scale_down_depth=0.75,
                    replica_qps=replica_qps, up_ticks=2, down_ticks=6,
                    cooldown_s=2.0, max_step=1),
                interval_s=1.0, clock=clock)
        result = SoakDriver(router, trace(), clock=clock,
                            step_dt=step_dt, max_wall_s=240,
                            autoscaler=scaler).run()
        return result, router, scaler

    telemetry.enable()
    telemetry.reset()
    try:
        model.eval()
        static_res, _, _ = drive(autoscaled=False)
        wal_root = tempfile.mkdtemp(prefix="bench_autoscale_wal_")
        try:
            auto_res, auto_router, scaler = drive(
                autoscaled=True,
                journal=RouterJournal(os.path.join(wal_root, "wal"),
                                      fsync="off"))
            journaled_resizes = auto_router.fleet_info()["resizes"]
        finally:
            shutil.rmtree(wal_root, ignore_errors=True)
    finally:
        model.train()
        telemetry.disable(clear_override=True)
    static_sum, auto_sum = static_res.summary(), auto_res.summary()
    savings = 100.0 * (1.0 - auto_res.replica_steps
                       / max(1, static_res.replica_steps))
    return {"autoscale": {
        "step_dt_s": step_dt,
        "ttft_p95_static_s": (static_sum["lanes"]
                              .get("interactive", {})
                              .get("ttft_p95_s")),
        "ttft_p95_autoscaled_s": (auto_sum["lanes"]
                                  .get("interactive", {})
                                  .get("ttft_p95_s")),
        "replica_steps_static": static_res.replica_steps,
        "replica_steps_autoscaled": auto_res.replica_steps,
        "replica_step_savings_pct": round(savings, 2),
        "burst_reaction_s": max(scaler.reactions, default=None),
        "grows": sum(1 for a in scaler.actions
                     if a["action"] == "grow"),
        "shrinks": sum(1 for a in scaler.actions
                       if a["action"] == "shrink"),
        "journaled_resizes": journaled_resizes,
        "lost_sessions": (auto_sum["sessions"]
                          - auto_sum["outcomes"].get("finished", 0)),
    }}


def bench_multimodel(model, cfg, on_tpu: bool) -> dict:
    """Batched multi-LoRA decode A/B (ISSUE 17): the same requests —
    three hosted models (the base + two LoRA fine-tunes over it) —
    served MIXED in one engine's single ragged dispatch per step vs
    ADAPTER-SERIAL (one model's requests at a time on an identically
    shaped engine — the fragmented-fleet cost model). Greedy streams
    must be bit-identical between the two shapes (the lora_epilogue
    row-gather is exact: row 0 is an all-zeros no-adapter row, ranks
    pad with exact-zero columns). Returns a detail sub-dict;
    `multimodel_decode_tokens_per_sec` (the mixed row) is wired into
    REGRESSION_METRICS."""
    import numpy as np
    import paddle_tpu.observability as telemetry
    from paddle_tpu.models.serving import ContinuousBatchingEngine
    from paddle_tpu.serving import FleetModelStore, split_model_id

    model.eval()
    if on_tpu:
        per, p_len, warm, steps, max_seq = 4, 128, 8, 64, 1024
    else:
        per, p_len, warm, steps, max_seq = 2, 8, 2, 6, 64
    rng = np.random.default_rng(0)
    sd = dict(model.state_dict())
    targets = ("model.layers.0.self_attn.q_proj.weight",
               "model.layers.1.mlp.gate_proj.weight")

    def deltas():
        out = {}
        for nm in targets:
            k, n = sd[nm].shape
            out[nm] = (
                rng.normal(size=(k, 4)).astype(np.float32) * 0.05,
                rng.normal(size=(4, n)).astype(np.float32) * 0.05)
        return out

    store = FleetModelStore(base_model="base", max_rank=8)
    mids = ["base",
            store.register_adapter("a1", deltas()),
            store.register_adapter("a2", deltas())]
    prompts = {mid: [list(rng.integers(1, cfg.vocab_size, p_len))
                     for _ in range(per)] for mid in mids}

    def build(tag):
        # identical engine shape for both arms: the serial arm pays
        # fragmentation (empty slots), not a smaller compiled batch
        eng = ContinuousBatchingEngine(
            model, max_batch_size=3 * per, max_seq_len=max_seq)
        for mid in mids:
            store.ensure(tag, eng, mid)
        return eng

    def run(tag, eng, model_ids):
        # per-engine request_ids collide across arms, so key the
        # harvested streams by (model, prompt index) instead
        key = {}
        for mid in model_ids:
            for j, p in enumerate(prompts[mid]):
                rid = eng.add_request(
                    list(p), max_new_tokens=max_seq - p_len - 1,
                    adapter=split_model_id(mid)[1])
                key[str(rid)] = (mid, j)
        for _ in range(warm):
            eng.step()
        # ISSUE 20: telemetry goes on at the window boundary — warm-
        # minted programs flipped their first-call flag already, so
        # only an in-window compile can trip the steady-state gate
        telemetry.enable()
        telemetry.reset()
        try:
            t0 = time.perf_counter()
            for _ in range(steps):
                eng.step()
            dt = time.perf_counter() - t0
            _assert_steady_state(f"bench_multimodel[{tag}]",
                                 telemetry.snapshot())
        finally:
            telemetry.disable(clear_override=True)
        streams = {}
        for r in eng._slot_req:
            if r is not None:
                streams[key[str(r.request_id)]] = list(r.output)
        return dt, streams

    # mixed: all three models share every decode step's one ragged
    # dispatch
    mixed_dt, mixed_streams = run("mixed", build("mixed"), mids)
    mixed_tps = 3 * per * steps / mixed_dt
    # adapter-serial: one model's requests at a time, fresh engine each
    serial_dt, serial_streams = 0.0, {}
    for mid in mids:
        dt, streams = run(f"serial-{mid}", build(f"serial-{mid}"),
                          [mid])
        serial_dt += dt
        serial_streams.update(streams)
    serial_tps = 3 * per * steps / serial_dt

    bit_identical = mixed_streams == serial_streams \
        and len(mixed_streams) == 3 * per
    return {"multimodel": {
        "models": len(mids), "requests": 3 * per,
        "multimodel_decode_tokens_per_sec": round(mixed_tps, 1),
        "adapter_serial_decode_tokens_per_sec": round(serial_tps, 1),
        "mixed_over_serial_speedup": round(mixed_tps / serial_tps, 3),
        "bit_identical": bit_identical,
    }}


def bench_paged_attention(on_tpu: bool) -> dict:
    """Paged-attention microbench (ISSUE 6): the legacy q=1 kernel vs
    the ragged kernel vs the unbounded XLA gather path, at a decode
    shape and a mixed prefill+decode shape. On TPU the first two run
    the Pallas kernels; on the CPU fallback they run their XLA oracles
    (the ragged one gather-BOUNDED to the referenced block-table
    prefix), so the CPU numbers measure the trim + one-dispatch
    packing win and the TPU numbers the kernel itself. Returns a
    detail sub-dict gated by --check-regression."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from paddle_tpu.ops.paged_attention import paged_attention_values
    from paddle_tpu.ops import ragged_paged_attention as rpa

    if on_tpu:
        hk, g, d, ps = 8, 2, 64, 16
        s_max, decode_b, decode_ctx = 2048, 32, 1024
        prefill_len, n_prefill, n_decode = 512, 4, 28
        reps = 10
    else:
        hk, g, d, ps = 2, 2, 32, 16
        s_max, decode_b, decode_ctx = 256, 4, 64
        prefill_len, n_prefill, n_decode = 32, 2, 4
        reps = 3
    h = hk * g
    pps = s_max // ps
    rng = np.random.default_rng(0)
    dt = jnp.bfloat16 if on_tpu else jnp.float32

    def _pool(n_seqs):
        num_pages = n_seqs * pps + 1
        kp = jnp.asarray(rng.standard_normal(
            (hk, num_pages, ps, d)).astype(np.float32), dt)
        vp = jnp.asarray(rng.standard_normal(
            (hk, num_pages, ps, d)).astype(np.float32), dt)
        bt = (np.arange(n_seqs * pps, dtype=np.int32)
              .reshape(n_seqs, pps) + 1)
        return kp, vp, bt

    def _time(f, *a):
        np.asarray(jax.device_get(f(*a)))          # compile + warm
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            np.asarray(jax.device_get(f(*a)))      # D2H sync discipline
            best = min(best, time.perf_counter() - t0)
        return best

    def _gather_full(q, kp, vp, qs, ql, cl, bt):
        """The pre-trim baseline: gather the FULL block table, then the
        shared masked core — what `_paged_xla` cost before ISSUE 6."""
        t = q.shape[0]
        kc, vc = rpa.gather_pages(kp, vp, jnp.asarray(bt),
                                  pages_bound=bt.shape[1])
        seq_t, pos_t = rpa.token_arrays(qs, ql, cl, t)
        tok_seq = np.maximum(seq_t, 0)
        ctx_t = np.where(seq_t >= 0, cl[tok_seq], 0)
        qh = q.reshape(t, hk, g, d)
        out = rpa.masked_page_attention(
            qh, kc[tok_seq], vc[tok_seq],
            jnp.asarray(np.where(seq_t >= 0, pos_t, -1)),
            jnp.asarray(ctx_t), 1.0 / (d ** 0.5))
        return out.reshape(t, h, d)

    out = {}
    # -- decode shape: B sequences x 1 query token ---------------------
    kp, vp, bt = _pool(decode_b)
    ctx = rng.integers(decode_ctx // 2, decode_ctx,
                       decode_b).astype(np.int32)
    q1 = jnp.asarray(rng.standard_normal(
        (decode_b, h, d)).astype(np.float32), dt)
    qs1 = np.arange(decode_b, dtype=np.int32)
    ql1 = np.ones(decode_b, np.int32)
    t_legacy = _time(jax.jit(lambda q, k, v: paged_attention_values(
        q, k, v, jnp.asarray(ctx), jnp.asarray(bt))), q1, kp, vp)
    t_ragged = _time(jax.jit(lambda q, k, v:
                             rpa.ragged_paged_attention_values(
                                 q, k, v, qs1, ql1, ctx, bt,
                                 block_q=1)), q1, kp, vp)
    t_gather = _time(jax.jit(lambda q, k, v: _gather_full(
        q, k, v, qs1, ql1, ctx, bt)), q1, kp, vp)
    out["decode"] = {
        "batch": decode_b, "ctx": int(decode_ctx), "pages_per_seq": pps,
        "legacy_kernel_ms": round(t_legacy * 1e3, 3),
        "ragged_ms": round(t_ragged * 1e3, 3),
        "xla_gather_ms": round(t_gather * 1e3, 3),
        "ragged_vs_gather_speedup": round(t_gather / t_ragged, 3),
    }
    out["decode_tokens_per_sec_ragged"] = round(decode_b / t_ragged, 1)
    # -- mixed prefill+decode shape: the ragged kernel's reason to
    # exist; the legacy kernel cannot express it -----------------------
    n_seqs = n_prefill + n_decode
    kp, vp, bt = _pool(n_seqs)
    ql = np.array([prefill_len] * n_prefill + [1] * n_decode, np.int32)
    cl = np.array([prefill_len] * n_prefill
                  + list(rng.integers(decode_ctx // 2, decode_ctx,
                                      n_decode)), np.int32)
    qs, total = rpa.pack_ragged_starts(ql, block_q=8)
    qm = jnp.asarray(rng.standard_normal(
        (total, h, d)).astype(np.float32), dt)
    tokens = int(ql.sum())
    t_ragged = _time(jax.jit(lambda q, k, v:
                             rpa.ragged_paged_attention_values(
                                 q, k, v, qs, ql, cl, bt,
                                 block_q=8)), qm, kp, vp)
    t_gather = _time(jax.jit(lambda q, k, v: _gather_full(
        q, k, v, qs, ql, cl, bt)), qm, kp, vp)
    out["mixed"] = {
        "prefills": n_prefill, "prefill_len": prefill_len,
        "decodes": n_decode, "query_tokens": tokens,
        "ragged_ms": round(t_ragged * 1e3, 3),
        "xla_gather_ms": round(t_gather * 1e3, 3),
        "ragged_vs_gather_speedup": round(t_gather / t_ragged, 3),
    }
    out["mixed_tokens_per_sec_ragged"] = round(tokens / t_ragged, 1)
    return {"paged_attention": out}


def bench_int8(on_tpu: bool) -> dict:
    """int8-vs-bf16 MXU matmul timing (VERDICT r4 weak #5: the 2x claim
    needs a driver-captured artifact). Returns a detail sub-dict."""
    import jax
    import jax.numpy as jnp

    from jax import lax

    m = 4096 if on_tpu else 256
    xb = jnp.ones((m, m), jnp.bfloat16)
    x8 = jnp.ones((m, m), jnp.int8)

    # slope method (r5 chip gate): the axon tunnel adds ~64ms per
    # synchronous roundtrip, so single-dispatch timings measure
    # transport. N dependent matmuls inside one executable at two N
    # values; the slope cancels every fixed cost. Measured on v5e:
    # bf16 213 TF/s (nominal peak), int8 260 TOP/s -> 1.22x real.
    def chain_bf(n):
        def f(a, b):
            def body(i, carry):
                a_, acc = carry
                o = a_ @ b
                return (o * jnp.bfloat16(1e-4) + a_ * jnp.bfloat16(0.5),
                        acc + o[0, 0].astype(jnp.float32))
            return lax.fori_loop(0, n, body, (a, jnp.float32(0)))[1]
        return jax.jit(f)

    def chain_i8(n):
        def f(a, b):
            def body(i, carry):
                a_, acc = carry
                o = lax.dot_general(a_, b, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.int32)
                return ((o & 1).astype(jnp.int8), acc + o[0, 0])
            return lax.fori_loop(0, n, body, (a, jnp.int32(0)))[1]
        return jax.jit(f)

    def t(f, a):
        # min over repeats: a single scheduler hiccup in either run
        # would otherwise flip the slope sign
        jax.device_get(f(a, a))              # compile + warm
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            jax.device_get(f(a, a))
            best = min(best, time.perf_counter() - t0)
        return best

    n_lo, n_hi = (4, 20) if on_tpu else (1, 3)
    span = n_hi - n_lo
    t_bf = (t(chain_bf(n_hi), xb) - t(chain_bf(n_lo), xb)) / span
    t_i8 = (t(chain_i8(n_hi), x8) - t(chain_i8(n_lo), x8)) / span
    if t_bf <= 0 or t_i8 <= 0:
        return {"int8_timing_error":
                f"non-positive slope (bf16 {t_bf:.2e}, int8 {t_i8:.2e})"}
    return {
        "int8_matmul_ms": round(t_i8 * 1e3, 3),
        "bf16_matmul_ms": round(t_bf * 1e3, 3),
        "bf16_matmul_tflops": round(2 * m ** 3 / t_bf / 1e12, 1),
        "int8_matmul_tops": round(2 * m ** 3 / t_i8 / 1e12, 1),
        "int8_speedup_vs_bf16": round(t_bf / t_i8, 3),
    }


def bench_quant(model, cfg, on_tpu: bool) -> dict:
    """Quantized-vs-full-width serving A/B (ISSUE 15): decode
    tokens/sec, CONCURRENT RESIDENCY at fixed pool bytes (the
    half-width-page prize: how many requests' KV fit the same HBM),
    migration payload quantiles, and the end-to-end logit error of the
    quantized engine against the full-width one on fixed prompts
    (compared per decode step only while the two token streams still
    agree — after a divergence the positions differ and the rows stop
    being comparable). Returns a detail sub-dict;
    `quant_decode_tokens_per_sec` is gated by REGRESSION_METRICS."""
    import numpy as np
    import paddle_tpu.observability as telemetry
    from paddle_tpu.models.serving import (ContinuousBatchingEngine,
                                           QuantServingConfig)
    from paddle_tpu.serving.transfer import payload_nbytes

    model.eval()
    if on_tpu:
        slots, p_len, warm, steps, max_seq = 8, 128, 8, 64, 1024
    else:
        slots, p_len, warm, steps, max_seq = 2, 8, 2, 6, 64
    rng = np.random.default_rng(0)
    quant = QuantServingConfig(weights="int8", kv="int8")

    class _Recorder:
        """Minimal sentry-shaped logit recorder (attach_sentry
        contract): pulls every step's sampled-row logits to host."""
        wants_logits = True

        def __init__(self):
            self.logits, self.trips = [], 0

        def step_tick(self):
            return True

        def observe_tokens(self, toks):
            pass

        def observe_logits(self, lg):
            self.logits.append(np.asarray(lg, np.float32))

        def note_cost(self, s):
            pass

    def build(q, num_pages=None, batch=slots, sentry=None):
        eng = ContinuousBatchingEngine(
            model, max_batch_size=batch, max_seq_len=max_seq,
            num_pages=num_pages, quant=q)
        if sentry is not None:
            eng.attach_sentry(sentry)
        return eng

    out = {}
    # -- decode throughput + logit error, one warm engine per mode ----
    toks_per_sec, recorders, streams = {}, {}, {}
    prompts = [list(rng.integers(1, cfg.vocab_size, p_len))
               for _ in range(slots)]
    for name, q in (("fp", None), ("quant", quant)):
        rec = _Recorder()
        eng = build(q, sentry=rec)
        for p in prompts:
            eng.add_request(list(p), max_new_tokens=max_seq - p_len - 1)
        for _ in range(warm):
            eng.step()
        # ISSUE 20: verified-compile-free timed window (see
        # bench_multimodel's run() for the boundary semantics)
        telemetry.enable()
        telemetry.reset()
        try:
            t0 = time.perf_counter()
            for _ in range(steps):
                eng.step()
            dt = time.perf_counter() - t0
            _assert_steady_state(f"bench_quant[{name}]",
                                 telemetry.snapshot())
        finally:
            telemetry.disable(clear_override=True)
        toks_per_sec[name] = round(slots * steps / dt, 1)
        recorders[name] = rec
        streams[name] = [list(r.output) for r in eng._slot_req
                         if r is not None]
    out["fp_decode_tokens_per_sec"] = toks_per_sec["fp"]
    out["quant_decode_tokens_per_sec"] = toks_per_sec["quant"]
    out["quant_decode_speedup"] = round(
        toks_per_sec["quant"] / toks_per_sec["fp"], 3)
    # logit error over the agreeing stream prefix (steps compare 1:1
    # until the first token divergence)
    err, agree = 0.0, 0
    for a, b in zip(recorders["fp"].logits, recorders["quant"].logits):
        if a.shape != b.shape:
            break
        err = max(err, float(np.max(np.abs(a - b))))
        agree += 1
        if [s[:agree] for s in streams["fp"]] \
                != [s[:agree] for s in streams["quant"]]:
            break
    out["logit_max_abs_err"] = round(err, 4)
    out["logit_steps_compared"] = agree
    # -- concurrent residency at FIXED pool bytes ---------------------
    # budget = what 2 full-width slots' worst case costs; each mode
    # gets num_pages = budget // its own page_bytes (scales included —
    # cache_memory_info is the honest bill)
    probe_fp = build(None, batch=1)
    probe_q = build(quant, batch=1)
    pb_fp = probe_fp.cache_memory_info()["page_bytes"]
    pb_q = probe_q.cache_memory_info()["page_bytes"]
    budget = pb_fp * (2 * (-(-max_seq // probe_fp.page_size)))
    res = {}
    for name, q, pb in (("fp", None, pb_fp), ("quant", quant, pb_q)):
        eng = build(q, num_pages=max(2, budget // pb + 1), batch=64)
        for _ in range(64):
            eng.add_request(
                list(rng.integers(1, cfg.vocab_size, p_len)),
                max_new_tokens=max_seq - p_len - 1)
        peak = 0
        for _ in range(3):
            eng.step()
            peak = max(peak, sum(r is not None
                                 for r in eng._slot_req))
        res[name] = peak
    out["residency_at_fixed_bytes"] = res
    out["page_bytes"] = {"fp": pb_fp, "quant": pb_q}
    out["residency_ratio"] = round(res["quant"] / max(res["fp"], 1), 3)
    # -- migration payload bytes --------------------------------------
    ratios = []
    for n in (p_len, 2 * p_len, 3 * p_len):
        pair = {}
        for name, q in (("fp", None), ("quant", quant)):
            eng = build(q)
            rid = eng.add_request(
                list(rng.integers(1, cfg.vocab_size, n)),
                max_new_tokens=8)
            eng.step()
            pair[name] = payload_nbytes(eng.export_pages(rid))
        ratios.append(pair["quant"] / pair["fp"])
    ratios.sort()
    out["payload_bytes_ratio"] = {
        "p50": round(ratios[len(ratios) // 2], 3),
        "max": round(ratios[-1], 3)}
    return {"quant": out}


def bench_journal(model, cfg, on_tpu: bool) -> dict:
    """Durability A/B (ISSUE 13): decode tokens/sec of a journaled
    router vs a journal-free one, per fsync policy, plus recovery-time
    quantiles for a 200-request write-ahead journal. The acceptance
    bar: fsync="terminal" (the default — submit/terminal records pay
    the disk round-trip, per-step progress mirrors do not) costs <= 3%
    decode throughput on the CPU oracle. Returns a detail sub-dict;
    `journal_on_decode_tokens_per_sec` (the fsync="terminal" row) is
    wired into REGRESSION_METRICS."""
    import shutil
    import tempfile

    import numpy as np
    import paddle_tpu.observability as telemetry
    from paddle_tpu.models.serving import ContinuousBatchingEngine
    from paddle_tpu.serving import RouterJournal, ServingRouter

    model.eval()
    if on_tpu:
        slots, p_len, warm, steps, max_seq = 8, 128, 8, 64, 1024
    else:
        # max_seq sized so every request OUTLASTS the whole measured
        # window (3 interleaved modes + the separate fsync="step"
        # block) — an emptying batch would hand the later modes
        # cheaper steps
        # slots=4: the journal's per-step cost is ONE batched record
        # regardless of batch size, so a representative (not
        # degenerately small) decode step is the honest denominator
        slots, p_len, warm, steps, max_seq = 4, 8, 3, 56, 256
    rng = np.random.default_rng(0)
    jobs = [list(rng.integers(1, cfg.vocab_size, p_len))
            for _ in range(slots)]
    root = tempfile.mkdtemp(prefix="pdt_bench_journal_")
    telemetry.enable()

    def fleet(journal):
        return ServingRouter(
            lambda i: ContinuousBatchingEngine(
                model, max_batch_size=slots, max_seq_len=max_seq,
                attention_impl=ATTENTION_IMPL),
            num_replicas=1, journal=journal)

    detail = {}
    try:
        # A/B on ONE warm fleet, the modes interleaved per block so
        # every mode samples the same engine state and machine phase.
        # tokens/sec per mode comes from each mode's pooled step-time
        # median; the OVERHEAD bar does NOT — this container drifts
        # 10%+ between runs and stalls for ~100 ms at a time (visible
        # as replay p95 >> p50 below), and an all-bare calibration run
        # of the block harness read a 3.4% "overhead" between
        # IDENTICAL modes, so differencing two noisy step-time medians
        # cannot resolve a 3% bar. The journal's cost is pure serial
        # time added inside the step (one batched progress append — a
        # dict diff, one json dump, one buffered write, plus the
        # policy's fsync), so `_TimedJournal` clocks exactly that work
        # in situ and overhead_pct = journal-seconds per step over the
        # bare step time. fsync="step" runs LAST: its ~10 ms fsync
        # stalls leave a flush backlog that would poison neighboring
        # modes' samples (a per-step rotation showed the bare-router
        # BASELINE 10% slower than the journaled modes — flattering,
        # and wrong).

        class _TimedJournal:
            """Delegating wrapper that accumulates wall time spent in
            the journal calls on the router's step path."""

            def __init__(self, inner):
                self._inner = inner
                self.spent = 0.0

            def __getattr__(self, name):
                return getattr(self._inner, name)

            def step_mirror(self, mirrors):
                t0 = time.perf_counter()
                try:
                    return self._inner.step_mirror(mirrors)
                finally:
                    self.spent += time.perf_counter() - t0

            def append_terminal(self, *a, **kw):
                t0 = time.perf_counter()
                try:
                    return self._inner.append_terminal(*a, **kw)
                finally:
                    self.spent += time.perf_counter() - t0

        router = fleet(None)
        ids = [router.submit(p, max_new_tokens=max_seq - p_len - 1)
               for p in jobs]
        jrs = {None: None}
        for mode in ("off", "terminal", "step"):
            jr = RouterJournal(os.path.join(root, f"wal-{mode}"),
                               fsync=mode)
            for rid, p in zip(ids, jobs):
                # the submits this journal would have seen had it been
                # attached from construction
                jr.append_submit(request_id=rid, prompt=p,
                                 max_new_tokens=max_seq - p_len - 1)
            jrs[mode] = _TimedJournal(jr)
        for _ in range(warm):
            router.step()
        warm_snap = telemetry.snapshot()  # ISSUE 20 steady-state gate
        cycle = (None, "off", "terminal")
        block = max(4, steps // 10)
        step_times = {m: [] for m in cycle + ("step",)}
        journal_times = {m: [] for m in cycle + ("step",)}
        for c in range(steps // block):
            for mode in cycle:
                router.journal = jrs[mode]
                for _ in range(block):
                    if mode is not None:
                        jrs[mode].spent = 0.0
                    t0 = time.perf_counter()
                    router.step()
                    step_times[mode].append(time.perf_counter() - t0)
                    if mode is not None:
                        journal_times[mode].append(jrs[mode].spent)
        router.journal = jrs["step"]
        for _ in range(steps // 2):
            jrs["step"].spent = 0.0
            t0 = time.perf_counter()
            router.step()
            step_times["step"].append(time.perf_counter() - t0)
            journal_times["step"].append(jrs["step"].spent)
        _assert_steady_state("bench_journal", telemetry.snapshot(),
                             warm_snap)
        router.journal = None
        for tj in jrs.values():
            if tj is not None:
                tj.close()
        med = {m: sorted(v)[len(v) // 2] for m, v in step_times.items()}
        detail["journal_off_decode_tokens_per_sec"] = \
            round(slots / med[None], 1)
        for mode in ("off", "terminal", "step"):
            jt = journal_times[mode]
            j_med = sorted(jt)[len(jt) // 2]
            detail[f"fsync_{mode}"] = {
                "decode_tokens_per_sec": round(slots / med[mode], 1),
                "journal_us_per_step": round(j_med * 1e6, 1),
                "overhead_pct": round(j_med / med[None] * 100, 2),
            }
        detail["journal_on_decode_tokens_per_sec"] = \
            detail["fsync_terminal"]["decode_tokens_per_sec"]

        # recovery-time quantiles for a 200-request journal: submits +
        # one batched progress record each, a quarter already terminal
        # (the dedupe path), replayed fresh N times for the quantiles
        # plus one full recover() (replay + rehydrate-dispatch)
        n_req = 200
        wal = os.path.join(root, "wal-recovery")
        with RouterJournal(wal, fsync="off",
                           compact_finalized=None) as jr:
            for i in range(n_req):
                rid = f"req-{i}"
                jr.append_submit(request_id=rid,
                                 prompt=jobs[i % slots],
                                 max_new_tokens=max_seq - p_len - 1)
                jr.step_mirror({rid: [int(t) for t in
                                      jobs[i % slots][:4]]})
                if i % 4 == 0:
                    jr.append_terminal(rid, "finished",
                                       [int(t) for t in
                                        jobs[i % slots][:4]])
        # ONE journal object for the timing loop: every RouterJournal
        # open appends a fresh segment, so per-iteration construction
        # would grow the journal under its own measurement (and leak
        # the open handles)
        replay_ms = []
        with RouterJournal(wal, fsync="off") as jr2:
            for _ in range(20):
                t0 = time.perf_counter()
                rep = jr2.replay()
                replay_ms.append((time.perf_counter() - t0) * 1e3)
            journal_bytes = jr2.stats()["bytes"]
        assert len(rep.live) + len(rep.finished) == n_req
        replay_ms.sort()
        t0 = time.perf_counter()
        recovered = ServingRouter.recover(
            RouterJournal(wal, fsync="off"),
            lambda i: ContinuousBatchingEngine(
                model, max_batch_size=slots, max_seq_len=max_seq,
                attention_impl=ATTENTION_IMPL),
            num_replicas=1)
        recover_wall = time.perf_counter() - t0
        detail["recovery"] = {
            "requests": n_req,
            "live": len(rep.live),
            "deduped": len(rep.finished),
            "replay_p50_ms": round(replay_ms[len(replay_ms) // 2], 3),
            "replay_p95_ms": round(
                replay_ms[int(len(replay_ms) * 0.95)], 3),
            "recover_wall_s": round(recover_wall, 4),
            "journal_bytes": journal_bytes,
        }
        assert len(recovered.requests) == n_req
        recovered.journal.close()
    finally:
        telemetry.disable(clear_override=True)
        model.train()
        shutil.rmtree(root, ignore_errors=True)
    return {"journal": detail}


def bench_sentry(model, cfg, on_tpu: bool) -> dict:
    """Gray-failure defense overhead (ISSUE 14): decode tokens/sec
    with numeric sentries off / every-step / every-Nth on warm
    fleets, plus canary probe wall-time quantiles. The acceptance
    bar: the every-Nth scan mode (the production default) costs <= 3%
    decode tokens/sec vs sentries-off.

    Measurement discipline = PR 13's: this container's step-time
    differencing swings +-10% between identical configs, so the 3%
    bar is graded SURGICALLY — the sentry accumulates its own in-step
    wall seconds (`NumericSentry.spent`: token checks, the logit
    host pull, the scan) and overhead_pct = sentry-seconds per step
    over the sentries-OFF fleet's median step. Three separate warm
    fleets (not one fleet with swapped sentries): `attach_sentry`
    rebuilds the decode program, and mid-measurement recompiles would
    poison every neighboring block. One cost `spent` cannot see: the
    sentry variant's decode program RETURNS its sampled-row logits
    (an extra output buffer per dispatch) — the per-mode
    decode_tokens_per_sec rows bound that side visibly, noise
    notwithstanding, next to the surgical number. Returns a detail
    sub-dict;
    `sentry_on_decode_tokens_per_sec` (the every-Nth row) is wired
    into REGRESSION_METRICS."""
    import numpy as np
    import paddle_tpu.observability as telemetry
    from paddle_tpu.models.serving import ContinuousBatchingEngine
    from paddle_tpu.serving import (CanaryConfig, SentryConfig,
                                    ServingRouter)

    model.eval()
    if on_tpu:
        slots, p_len, warm, steps, max_seq, nth = 8, 128, 8, 64, 1024, 8
    else:
        # max_seq sized so every request outlasts the measured window
        # (an emptying batch hands later steps a cheaper batch)
        slots, p_len, warm, steps, max_seq, nth = 4, 8, 3, 48, 256, 8
    rng = np.random.default_rng(0)
    jobs = [list(rng.integers(1, cfg.vocab_size, p_len))
            for _ in range(slots)]
    telemetry.enable()
    detail = {}
    try:
        def fleet(sentry):
            # the canary is mandatory alongside a sentry; a huge
            # interval keeps it inert through the measured window
            # (the quantile section below turns it on explicitly)
            return ServingRouter(
                lambda i: ContinuousBatchingEngine(
                    model, max_batch_size=slots + 1,
                    max_seq_len=max_seq,
                    attention_impl=ATTENTION_IMPL),
                num_replicas=1, sentry=sentry,
                canary=None if sentry is None
                else CanaryConfig(interval=3600.0))

        modes = {"off": None,
                 "every_step": SentryConfig(scan_every=1),
                 "every_nth": SentryConfig(scan_every=nth)}
        step_med, spent_med = {}, {}
        routers = {}
        for mode, scfg in modes.items():
            router = fleet(scfg)
            routers[mode] = router
            for p in jobs:
                router.submit(p, max_new_tokens=max_seq - p_len - 1)
            for _ in range(warm):
                router.step()
            warm_snap = telemetry.snapshot()  # ISSUE 20
            h = router.replicas[0]
            st, sp = [], []
            for _ in range(steps):
                if h.sentry is not None:
                    h.sentry.spent = 0.0
                t0 = time.perf_counter()
                router.step()
                st.append(time.perf_counter() - t0)
                if h.sentry is not None:
                    sp.append(h.sentry.spent)
            _assert_steady_state(f"bench_sentry[{mode}]",
                                 telemetry.snapshot(), warm_snap)
            step_med[mode] = sorted(st)[len(st) // 2]
            spent_med[mode] = (sorted(sp)[len(sp) // 2] if sp else 0.0)
        bare = step_med["off"]
        detail["sentry_off_decode_tokens_per_sec"] = \
            round(slots / bare, 1)
        for mode in ("every_step", "every_nth"):
            h = routers[mode].replicas[0]
            detail[mode] = {
                "decode_tokens_per_sec": round(
                    slots / step_med[mode], 1),
                "sentry_us_per_step": round(spent_med[mode] * 1e6, 1),
                "overhead_pct": round(
                    spent_med[mode] / bare * 100, 2),
                "scans": h.sentry.scans, "trips": h.sentry.trips,
            }
        detail["sentry_on_decode_tokens_per_sec"] = \
            detail["every_nth"]["decode_tokens_per_sec"]

        # canary wall-time quantiles: wake the every-Nth fleet's
        # scheduled probe and run several rounds to a verdict each
        router = routers["every_nth"]
        router.canary_cfg.interval = 1e-9
        h = router.replicas[0]
        want = 6 if not on_tpu else 10
        for _ in range(4000):
            router.step()
            if h.canary_runs >= want:
                break
        snap = telemetry.snapshot()["histograms"]
        canary = snap.get("pdt_sentry_canary_seconds", {}).get("")
        detail["canary"] = {
            "runs": h.canary_runs,
            "passes": int(telemetry.value(
                "pdt_sentry_canary_runs_total", result="pass")),
            "wall_quantiles_s": _hist_quantiles(canary),
        }
    finally:
        telemetry.disable(clear_override=True)
        model.train()
    return {"sentry": detail}


def bench_async_pipeline(model, cfg, on_tpu: bool) -> dict:
    """Pipelined-decode overlap A/B (ISSUE 18): full-stack
    (journal fsync="terminal" + every-Nth sentry) fleets at
    harvest_every k in {1, 4, 8}, grading the convergence gate —
    decode tokens/sec with everything ON converges to the bare-engine
    number as k grows, because journal appends, sentry checks, and
    mirror diffs all quantize to one batched harvest per window.

    Measurement discipline = PR 13's, adapted to windows: per-step
    medians would lie here (k-1 of every k steps skip the harvest
    entirely — the spiky harvest step IS the design), so every number
    is a TOTAL over the measured span. The overlap-stack cost is
    clocked in situ (`_TimedJournal` wall + `NumericSentry.spent`)
    and full_stack_pct = (wall - stack_seconds) / wall — the fraction
    of the fleet's step wall that is pure decode. This also
    re-measures `detail.journal`'s per-step journal cost at each k
    (`journal_us_per_step`): group-commit shrinks it ~k-fold.
    `async_decode_tokens_per_sec` (the k=8 full-stack row, committed
    tokens over wall) is wired into REGRESSION_METRICS."""
    import shutil
    import tempfile

    import numpy as np
    import paddle_tpu.observability as telemetry
    from paddle_tpu.models.serving import ContinuousBatchingEngine
    from paddle_tpu.serving import (CanaryConfig, RouterJournal,
                                    SentryConfig, ServingRouter)

    model.eval()
    if on_tpu:
        slots, p_len, warm, steps, max_seq, nth = 8, 128, 8, 64, 1024, 8
    else:
        # the measured span covers several whole windows at k=8;
        # max_seq sized so every request outlasts it
        slots, p_len, warm, steps, max_seq, nth = 4, 8, 4, 48, 256, 8
    rng = np.random.default_rng(0)
    jobs = [list(rng.integers(1, cfg.vocab_size, p_len))
            for _ in range(slots)]
    root = tempfile.mkdtemp(prefix="pdt_bench_async_")
    telemetry.enable()
    detail = {}

    class _TimedJournal:
        def __init__(self, inner):
            self._inner = inner
            self.spent = 0.0

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def step_mirror(self, mirrors):
            t0 = time.perf_counter()
            try:
                return self._inner.step_mirror(mirrors)
            finally:
                self.spent += time.perf_counter() - t0

        def append_terminal(self, *a, **kw):
            t0 = time.perf_counter()
            try:
                return self._inner.append_terminal(*a, **kw)
            finally:
                self.spent += time.perf_counter() - t0

    try:
        for k in (1, 4, 8):
            jr = _TimedJournal(RouterJournal(
                os.path.join(root, f"wal-k{k}"), fsync="terminal"))
            router = ServingRouter(
                lambda i: ContinuousBatchingEngine(
                    model, max_batch_size=slots + 1,
                    max_seq_len=max_seq,
                    attention_impl=ATTENTION_IMPL, harvest_every=k),
                num_replicas=1, journal=jr,
                sentry=SentryConfig(scan_every=nth),
                canary=CanaryConfig(interval=3600.0))
            for p in jobs:
                router.submit(p, max_new_tokens=max_seq - p_len - 1)
            for _ in range(warm):
                router.step()
            h = router.replicas[0]
            h.engine.quiesce()           # every mode starts at a
            jr.spent = 0.0               # window boundary
            h.sentry.spent = 0.0
            warm_snap = telemetry.snapshot()  # ISSUE 20
            tok0 = telemetry.value("pdt_serving_decode_tokens_total")
            t0 = time.perf_counter()
            for _ in range(steps):
                router.step()
            h.engine.quiesce()           # commit the tail window into
            wall = time.perf_counter() - t0   # the measured span
            _assert_steady_state(f"bench_async_pipeline[k{k}]",
                                 telemetry.snapshot(), warm_snap)
            committed = telemetry.value(
                "pdt_serving_decode_tokens_total") - tok0
            stack = jr.spent + h.sentry.spent
            detail[f"k{k}"] = {
                "full_stack_decode_tokens_per_sec": round(
                    committed / wall, 1),
                "journal_us_per_step": round(
                    jr.spent / steps * 1e6, 1),
                "sentry_us_per_step": round(
                    h.sentry.spent / steps * 1e6, 1),
                "stack_overhead_pct": round(stack / wall * 100, 2),
                "full_stack_pct": round(
                    (wall - stack) / wall * 100, 2),
            }
            jr.close()
        # the convergence gate (acceptance bar): at k=8 the
        # journal+sentry stack costs <= 5% of the step wall, i.e.
        # full-stack throughput >= 95% of bare-engine
        detail["convergence"] = {
            "k8_full_stack_pct": detail["k8"]["full_stack_pct"],
            "gate_pct": 95.0,
            "pass": bool(detail["k8"]["full_stack_pct"] >= 95.0),
        }
        detail["async_decode_tokens_per_sec"] = \
            detail["k8"]["full_stack_decode_tokens_per_sec"]
    finally:
        telemetry.disable(clear_override=True)
        model.train()
        shutil.rmtree(root, ignore_errors=True)
    return {"async_pipeline": detail}


def run_bench(on_tpu: bool) -> dict:
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                         synthetic_lm_batch)
    from paddle_tpu.optimizer import AdamW

    dev = jax.devices()[0]

    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1024,
                          intermediate_size=2816, num_hidden_layers=16,
                          num_attention_heads=16, num_key_value_heads=8,
                          max_position_embeddings=2048)
        batch, seq, steps = 8, 2048, 20
    else:  # CI / no chip: tiny sanity config
        cfg = LlamaConfig.tiny()
        batch, seq, steps = 2, 128, 3

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.to(dtype="bfloat16")
    # norms stay bf16-safe (they compute in fp32 internally)
    opt = AdamW(learning_rate=3e-4, parameters=model.parameters(),
                weight_decay=0.01, multi_precision=True)
    ids, labels = synthetic_lm_batch(batch, seq, cfg.vocab_size)

    step = paddle.jit.TrainStep(
        model, opt, loss_fn=lambda m, x, y: m(x, labels=y)[0])

    # warmup / compile. Sync via D2H transfer (float()), NOT
    # jax.block_until_ready: on the axon remote platform block_until_ready
    # returns immediately for queued-but-unfinished work (measured live:
    # 5 queued steps "block" in 1ms, then float() waits 8.6s), which made
    # the r2-era timing measure dispatch only.
    loss = step(ids, labels)
    float(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(ids, labels)
    final_loss = float(loss)
    dt = (time.perf_counter() - t0) / steps

    n_params = cfg.num_params()
    tokens = batch * seq
    # standard 6ND approximation + attention term
    attn_flops = (12 * cfg.num_hidden_layers * cfg.hidden_size * seq
                  * tokens)
    flops_per_step = 6.0 * n_params * tokens + attn_flops
    achieved = flops_per_step / dt

    # bf16 peak FLOP/s (not the 2x int8 marketing number: v5e bf16 peak is
    # 197 TF/s). CPU fallback: no meaningful "peak" — the 1e12 divisor only
    # keeps the JSON schema; the _cpu_ci metric name marks it non-comparable.
    peak = {"TPU v5 lite": 197e12, "TPU v5e": 197e12,
            "TPU v5p": 459e12, "TPU v4": 275e12}.get(
        str(dev.device_kind), 197e12 if on_tpu else 1e12)
    mfu = achieved / peak
    tok_per_sec = tokens / dt

    detail = {
        "device": str(dev.device_kind),
        "params": n_params,
        "batch": batch, "seq": seq,
        "step_time_s": round(dt, 4),
        "tokens_per_sec_per_chip": round(tok_per_sec, 1),
        "loss": final_loss,
    }
    # secondary numbers ride the same JSON line (VERDICT r4 #1); a
    # failure in one must not take down the headline metric
    try:
        detail.update(bench_decode(model, cfg, on_tpu))
    except Exception:
        detail["decode_error"] = traceback.format_exc(limit=3)[-400:]
    try:
        detail.update(bench_router(model, cfg, on_tpu))
    except Exception:
        detail["router_error"] = traceback.format_exc(limit=3)[-400:]
    try:
        detail.update(bench_disagg(model, cfg, on_tpu))
    except Exception:
        detail["disagg_error"] = traceback.format_exc(limit=3)[-400:]
    try:
        detail.update(bench_speculative(model, cfg, on_tpu))
    except Exception:
        detail["speculative_error"] = \
            traceback.format_exc(limit=3)[-400:]
    try:
        detail.update(bench_soak(model, cfg, on_tpu))
    except Exception:
        detail["soak_error"] = traceback.format_exc(limit=3)[-400:]
    try:
        detail.update(bench_tp(on_tpu))
    except Exception:
        detail["tp_error"] = traceback.format_exc(limit=3)[-400:]
    try:
        detail.update(bench_paged_attention(on_tpu))
    except Exception:
        detail["paged_attention_error"] = \
            traceback.format_exc(limit=3)[-400:]
    try:
        detail.update(bench_int8(on_tpu))
    except Exception:
        detail["int8_error"] = traceback.format_exc(limit=3)[-400:]
    try:
        detail.update(bench_quant(model, cfg, on_tpu))
    except Exception:
        detail["quant_error"] = traceback.format_exc(limit=3)[-400:]
    try:
        detail.update(bench_journal(model, cfg, on_tpu))
    except Exception:
        detail["journal_error"] = traceback.format_exc(limit=3)[-400:]
    try:
        detail.update(bench_sentry(model, cfg, on_tpu))
    except Exception:
        detail["sentry_error"] = traceback.format_exc(limit=3)[-400:]
    try:
        detail.update(bench_autoscale(model, cfg, on_tpu))
    except Exception:
        detail["autoscale_error"] = \
            traceback.format_exc(limit=3)[-400:]
    try:
        detail.update(bench_multimodel(model, cfg, on_tpu))
    except Exception:
        detail["multimodel_error"] = \
            traceback.format_exc(limit=3)[-400:]
    try:
        detail.update(bench_async_pipeline(model, cfg, on_tpu))
    except Exception:
        detail["async_pipeline_error"] = \
            traceback.format_exc(limit=3)[-400:]

    return {
        "metric": "llama_train_mfu" if on_tpu else "llama_train_mfu_cpu_ci",
        "value": round(mfu, 4),
        "unit": "fraction_of_peak",
        "vs_baseline": round(mfu / TARGET_MFU, 4),
        "detail": detail,
    }


def _parse_args(argv=None):
    ap = argparse.ArgumentParser(
        description="paddle_tpu bench (one JSON line on stdout)")
    ap.add_argument("--check-regression", metavar="PREV.json",
                    default=None,
                    help="after the run, diff tokens/sec metrics "
                         "against this prior bench JSON and exit "
                         "non-zero on regression")
    ap.add_argument("--current", metavar="CUR.json", default=None,
                    help="with --check-regression: compare two saved "
                         "results instead of running the bench")
    ap.add_argument("--regression-threshold", type=float, default=10.0,
                    metavar="PCT", help="allowed drop in percent "
                                        "(default 10)")
    return ap.parse_args(argv)


def _regression_verdict(prev_path: str, cur: dict,
                        threshold: float) -> int:
    with open(prev_path) as f:
        prev = json.load(f)
    regressions, compared = check_regression(prev, cur, threshold)
    if compared == 0:
        sys.stderr.write("bench: regression check compared 0 metrics "
                         "(malformed prev/current JSON?)\n")
        return 2
    for r in regressions:
        sys.stderr.write(f"bench: REGRESSION {r}\n")
    if not regressions:
        sys.stderr.write(f"bench: regression check OK "
                         f"({compared} metrics within "
                         f"{threshold:g}%)\n")
    return 1 if regressions else 0


def main(argv=None):
    args = _parse_args(argv)
    if args.current is not None:
        if not args.check_regression:
            sys.stderr.write("bench: --current requires "
                             "--check-regression\n")
            return 2
        with open(args.current) as f:
            cur = json.load(f)
        return _regression_verdict(args.check_regression, cur,
                                   args.regression_threshold)

    error = None
    on_tpu = False
    if SKIP_TPU:
        error = "PDT_BENCH_SKIP_TPU set; ran CPU fallback"
    elif os.environ.get("BENCH_FORCE_CPU"):
        error = "BENCH_FORCE_CPU set; ran CPU fallback"
    else:
        on_tpu = probe_tpu()
        if not on_tpu:
            error = ("TPU backend failed to initialize within "
                     f"{PROBE_TIMEOUT_S}s x2; ran CPU fallback")

    if not on_tpu:
        # sitecustomize already imported jax; config.update is the only
        # platform override that still works (see tests/conftest.py).
        # XLA_FLAGS is still honored because the backend itself has not
        # initialized yet (the TPU probe runs in a subprocess) — force
        # the 8-device host platform so the tp>=2 half of bench_tp (and
        # its detail.tp.tp2 regression gate) runs on the CPU fallback
        # instead of silently skipping on a 1-device platform.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        import jax
        jax.config.update("jax_platforms", "cpu")

    metric = "llama_train_mfu" if on_tpu else "llama_train_mfu_cpu_ci"

    # watchdog: the probe proves a FRESH process can init the backend, but
    # the parent's own init could still wedge (round-1 failure mode: a
    # stale grant). SIGALRM converts that hang into the error JSON line.
    import signal

    def _alarm(signum, frame):
        raise TimeoutError("bench watchdog expired (backend hang?)")

    # probe cost + verdict ride the JSON so the BENCH_r*.json trajectory
    # shows what probing cost this round (ISSUE 6 satellite)
    probe_detail = dict(PROBE_INFO) if PROBE_INFO else {"skipped": True}

    signal.signal(signal.SIGALRM, _alarm)
    signal.alarm(int(os.environ.get("BENCH_WATCHDOG_S", "1500")))
    try:
        result = run_bench(on_tpu)
    except BaseException:
        result = {
            "metric": metric, "value": 0.0,
            "unit": "fraction_of_peak", "vs_baseline": 0.0,
            "detail": {"tpu_probe": probe_detail},
            "error": ((error + "; ") if error else "")
            + traceback.format_exc(limit=5)[-1500:],
        }
        emit(result)
        return (_regression_verdict(args.check_regression, result,
                                    args.regression_threshold)
                if args.check_regression else 0)
    finally:
        signal.alarm(0)
    result.setdefault("detail", {})["tpu_probe"] = probe_detail
    if error:
        result["error"] = error
    emit(result)
    if args.check_regression:
        return _regression_verdict(args.check_regression, result,
                                   args.regression_threshold)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
