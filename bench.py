#!/usr/bin/env python
"""Benchmark: Llama causal-LM training step on the attached TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is MFU / 0.40 — the BASELINE.json north-star target MFU
(no published reference numbers exist; see BASELINE.md).

Model size is chosen to exercise the chip seriously while fitting one
v5e (≈16 GiB HBM) with AdamW fp32 state: ~340M params, bf16 compute.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main():
    import jax
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.nn import functional as F
    from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                         synthetic_lm_batch)
    from paddle_tpu.optimizer import AdamW

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"

    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1024,
                          intermediate_size=2816, num_hidden_layers=16,
                          num_attention_heads=16, num_key_value_heads=8,
                          max_position_embeddings=2048)
        batch, seq, steps = 8, 2048, 20
    else:  # CI / no chip: tiny sanity config
        cfg = LlamaConfig.tiny()
        batch, seq, steps = 2, 128, 3

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.to(dtype="bfloat16")
    # norms stay bf16-safe (they compute in fp32 internally)
    opt = AdamW(learning_rate=3e-4, parameters=model.parameters(),
                weight_decay=0.01, multi_precision=True)
    ids, labels = synthetic_lm_batch(batch, seq, cfg.vocab_size)

    step = paddle.jit.TrainStep(
        model, opt, loss_fn=lambda m, x, y: m(x, labels=y)[0])

    # warmup / compile
    loss = step(ids, labels)
    jax.block_until_ready(loss._value)

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(ids, labels)
    jax.block_until_ready(loss._value)
    dt = (time.perf_counter() - t0) / steps

    n_params = cfg.num_params()
    tokens = batch * seq
    # standard 6ND approximation + attention term
    attn_flops = (12 * cfg.num_hidden_layers * cfg.hidden_size * seq
                  * tokens)
    flops_per_step = 6.0 * n_params * tokens + attn_flops
    achieved = flops_per_step / dt

    peak = {"TPU v5 lite": 394e12, "TPU v5e": 394e12,
            "TPU v5p": 459e12, "TPU v4": 275e12}.get(
        str(dev.device_kind), 394e12 if on_tpu else 1e12)
    mfu = achieved / peak
    tok_per_sec = tokens / dt

    print(json.dumps({
        "metric": "llama_train_mfu" if on_tpu else "llama_train_mfu_cpu_ci",
        "value": round(mfu, 4),
        "unit": "fraction_of_peak",
        "vs_baseline": round(mfu / 0.40, 4),
        "detail": {
            "device": str(dev.device_kind),
            "params": n_params,
            "batch": batch, "seq": seq,
            "step_time_s": round(dt, 4),
            "tokens_per_sec_per_chip": round(tok_per_sec, 1),
            "loss": float(loss),
        },
    }))


if __name__ == "__main__":
    main()
